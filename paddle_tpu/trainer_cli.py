"""`paddle_trainer`-style command line (legacy TrainerMain.cpp + the
`paddle train` wrapper of scripts/submit_local.sh.in, re-homed):

    python -m paddle_tpu.trainer_cli --program_dir DIR --steps N \
        [--batch_size B] [--checkpoint_dir CK --checkpoint_every K] \
        [--save_dir OUT] [--log_every L]

Trains an exported program directory (native/demo_driver.py
export_train_program format — the same artifact the C++ demo_trainer
consumes) with no model script: synthetic batches shaped by the feed
spec, serial-numbered checkpoints with resume (contrib CheckpointConfig
semantics), and a final persistables save.  Exits non-zero if the loss
failed to improve (the demo_trainer.cc contract).
"""

import argparse
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.trainer_cli")
    ap.add_argument("--program_dir", required=True,
                    help="export_train_program output directory")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch_size", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--log_every", type=int, default=10)
    ap.add_argument("--checkpoint_dir", default=None)
    ap.add_argument("--checkpoint_every", type=int, default=50)
    ap.add_argument("--save_dir", default=None,
                    help="save persistables here after training")
    args = ap.parse_args(argv)

    import paddle_tpu as fluid
    from paddle_tpu.contrib.trainer import load_checkpoint, save_checkpoint
    from paddle_tpu.native.demo_driver import DemoTrainer

    t = DemoTrainer(args.program_dir, batch_size=args.batch_size,
                    seed=args.seed)
    start_step = 0
    if args.checkpoint_dir:
        with fluid.scope_guard(t.scope):
            state = load_checkpoint(t.exe, args.checkpoint_dir, t.main)
        if state is not None:
            start_step = int(state.get("step_id", 0))
            print("resumed from checkpoint at step %d" % start_step)

    first = last = None
    last_saved = start_step
    for step in range(start_step, args.steps):
        loss = t.step()
        if first is None:
            first = loss
        last = loss
        if args.log_every and (step + 1) % args.log_every == 0:
            print("step %d loss %.6f" % (step + 1, loss))
        if (args.checkpoint_dir
                and (step + 1) % args.checkpoint_every == 0):
            with fluid.scope_guard(t.scope):
                save_checkpoint(t.exe, args.checkpoint_dir, t.main,
                                trainer_args={"step_id": step + 1})
            last_saved = step + 1
    if args.checkpoint_dir and last_saved < args.steps:
        with fluid.scope_guard(t.scope):
            save_checkpoint(t.exe, args.checkpoint_dir, t.main,
                            trainer_args={"step_id": args.steps})

    if args.save_dir:
        with fluid.scope_guard(t.scope):
            fluid.io.save_persistables(t.exe, args.save_dir, t.main)
        print("saved persistables to %s" % args.save_dir)

    if first is None:
        print("nothing to do: start step %d >= steps %d"
              % (start_step, args.steps))
        return 0
    print("first loss %.6f last loss %.6f" % (first, last))
    return 0 if last < first else 1


if __name__ == "__main__":
    sys.exit(main())
