"""Shared helpers for op lowerings."""

import jax.numpy as jnp
import numpy as np

# Device dtype policy: TPU has no fast int64/float64 path; map them to 32-bit
# (the analog of the reference's kernel dtype selection).
_DTYPE_MAP = {
    "float64": jnp.float32,
    "int64": jnp.int32,
    "float32": jnp.float32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int32": jnp.int32,
    "int16": jnp.int16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
}

# Paddle framework.proto VarType ids (framework.proto:105) for scripts that
# pass numeric dtypes.
_PROTO_DTYPE = {
    0: "bool",
    1: "int16",
    2: "int32",
    3: "int64",
    4: "float16",
    5: "float32",
    6: "float64",
    19: "uint8",
    20: "int8",
    21: "bfloat16",
}


def jdt(dtype):
    """attr dtype (string / numpy / proto int) -> jnp dtype for device."""
    if isinstance(dtype, (int, np.integer)):
        dtype = _PROTO_DTYPE[int(dtype)]
    if not isinstance(dtype, str):
        dtype = np.dtype(dtype).name
    if dtype in _DTYPE_MAP:
        return _DTYPE_MAP[dtype]
    return jnp.dtype(dtype)


def bcast_y(x, y, axis):
    """Paddle elementwise broadcast: Y's shape aligns to X starting at
    `axis` (-1 = trailing). Reshape y so numpy broadcasting applies."""
    if x.ndim == y.ndim:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    # squeeze trailing 1s paddle allows
    yshape = list(y.shape)
    new_shape = [1] * axis + yshape + [1] * (x.ndim - axis - len(yshape))
    return y.reshape(new_shape)


def unary(fn):
    def lower(ctx, ins, attrs):
        return {"Out": [fn(ins["X"][0], attrs)]}

    return lower
