"""Shared helpers for op lowerings."""

import jax.numpy as jnp
import numpy as np

# Device dtype policy: TPU has no fast int64/float64 path; map them to 32-bit
# (the analog of the reference's kernel dtype selection).
_DTYPE_MAP = {
    "float64": jnp.float32,
    "int64": jnp.int32,
    "float32": jnp.float32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int32": jnp.int32,
    "int16": jnp.int16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
}

# Paddle framework.proto VarType ids (framework.proto:105) for scripts that
# pass numeric dtypes.
_PROTO_DTYPE = {
    0: "bool",
    1: "int16",
    2: "int32",
    3: "int64",
    4: "float16",
    5: "float32",
    6: "float64",
    19: "uint8",
    20: "int8",
    21: "bfloat16",
}


def jdt(dtype):
    """attr dtype (string / numpy / proto int) -> jnp dtype for device."""
    if isinstance(dtype, (int, np.integer)):
        dtype = _PROTO_DTYPE[int(dtype)]
    if not isinstance(dtype, str):
        dtype = np.dtype(dtype).name
    if dtype in _DTYPE_MAP:
        return _DTYPE_MAP[dtype]
    return jnp.dtype(dtype)


def bcast_y(x, y, axis):
    """Paddle elementwise broadcast: Y's shape aligns to X starting at
    `axis` (-1 = trailing). Reshape y so numpy broadcasting applies."""
    if x.ndim == y.ndim:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    # squeeze trailing 1s paddle allows
    yshape = list(y.shape)
    new_shape = [1] * axis + yshape + [1] * (x.ndim - axis - len(yshape))
    return y.reshape(new_shape)


def unary(fn):
    def lower(ctx, ins, attrs):
        return {"Out": [fn(ins["X"][0], attrs)]}

    return lower


def stable_compact(valid, x, axis=0):
    """Stably move the slots where ``valid`` is True to the front of
    ``x`` along ``axis``, zero the rest, and return (compacted, counts).

    The shared front-compaction idiom (argsort on the (invalid, position)
    key) behind the static-shape re-expressions of the reference's
    dynamic-size ops (cond_take, sequence_erase, sequence_concat,
    split_lod_tensor, split_ids).  valid: bool, shape x.shape[:axis+1];
    counts: valid count along ``axis`` (shape valid.shape[:-1]).
    """
    n = x.shape[axis]
    pos = jnp.arange(n, dtype=jnp.int32)
    pos = pos.reshape((1,) * axis + (n,))
    key = jnp.where(valid, 0, 1) * n + jnp.broadcast_to(pos, valid.shape)
    order = jnp.argsort(key, axis=axis)
    gidx = order.reshape(order.shape + (1,) * (x.ndim - axis - 1))
    gidx = jnp.broadcast_to(gidx, x.shape)
    compacted = jnp.take_along_axis(x, gidx, axis=axis)
    counts = jnp.sum(valid.astype(jnp.int32), axis=axis)
    live = jnp.broadcast_to(pos, valid.shape) < jnp.expand_dims(counts, axis)
    live = live.reshape(live.shape + (1,) * (x.ndim - axis - 1))
    compacted = jnp.where(live, compacted, 0)
    return compacted, counts
