"""Linear-chain CRF ops (operators/linear_chain_crf_op.cc,
crf_decoding_op.cc, chunk_eval_op.cc).

TPU design: the reference runs a per-sequence C++ forward/backward over LoD
rows; here sequences arrive padded [B, T, N] + Length [B], the alpha
recursion is a `lax.scan` over time (batched over B on the VPU), and the
gradient of the log-likelihood falls out of vjp of the scan — no
hand-written CRF backward.

Transition layout matches the reference (linear_chain_crf_op.cc): row 0 =
start weights a, row 1 = end weights b, rows 2.. = w[i][j] transition from
tag i to tag j.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


def _crf_norm(emission, transition, length):
    """log Z per sequence via forward algorithm. emission [B,T,N]."""
    b, t, n = emission.shape
    a = transition[0]
    w = transition[2:]  # [N, N]
    alpha0 = a[None, :] + emission[:, 0]  # [B, N]

    def step(alpha, inp):
        e_t, t_idx = inp  # [B, N], scalar
        # logsumexp over prev tag: alpha[prev] + w[prev, cur]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + w[None], axis=1) + e_t
        m = (t_idx < length)[:, None]
        alpha = jnp.where(m, nxt, alpha)
        return alpha, None

    ts = jnp.arange(1, t)
    alpha, _ = jax.lax.scan(step, alpha0, (jnp.swapaxes(emission, 0, 1)[1:], ts))
    bvec = transition[1]
    return jax.nn.logsumexp(alpha + bvec[None, :], axis=1)  # [B]


def _crf_path_score(emission, transition, label, length):
    b, t, n = emission.shape
    a, bvec, w = transition[0], transition[1], transition[2:]
    lab = label.astype(jnp.int32)
    pos = jnp.arange(t)[None, :]
    valid = pos < length[:, None]  # [B, T]
    em = jnp.take_along_axis(emission, lab[:, :, None], axis=2)[..., 0]
    score = jnp.sum(jnp.where(valid, em, 0.0), axis=1)
    score = score + a[lab[:, 0]]
    trans = w[lab[:, :-1], lab[:, 1:]]  # [B, T-1]
    tvalid = (pos[:, 1:] < length[:, None])
    score = score + jnp.sum(jnp.where(tvalid, trans, 0.0), axis=1)
    last = jnp.take_along_axis(lab, (length - 1)[:, None], axis=1)[:, 0]
    return score + bvec[last]


@register("linear_chain_crf", no_grad_inputs=("Label", "Length"))
def _linear_chain_crf(ctx, ins, attrs):
    emission = ins["Emission"][0]  # [B, T, N]
    transition = ins["Transition"][0]  # [N+2, N]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[..., 0]
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)
    logz = _crf_norm(emission, transition, length)
    score = _crf_path_score(emission, transition, label, length)
    ll = (logz - score).reshape(-1, 1)
    return {
        "LogLikelihood": [ll],
        "Alpha": [jax.lax.stop_gradient(jnp.exp(emission))],
        "EmissionExps": [jax.lax.stop_gradient(jnp.exp(emission))],
        "TransitionExps": [jax.lax.stop_gradient(jnp.exp(transition))],
    }


@register("crf_decoding", no_grad_inputs=("Emission", "Transition", "Label", "Length"))
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode. Output ViterbiPath [B, T] (padded positions 0); if
    Label is given, outputs 1 where decoded == label (the reference's
    evaluation mode)."""
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    b, t, n = emission.shape
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((b,), t, jnp.int32)
    a, bvec, w = transition[0], transition[1], transition[2:]

    alpha0 = a[None, :] + emission[:, 0]

    def step(alpha, inp):
        e_t, t_idx = inp
        scores = alpha[:, :, None] + w[None]  # [B, prev, cur]
        best_prev = jnp.argmax(scores, axis=1)  # [B, cur]
        nxt = jnp.max(scores, axis=1) + e_t
        m = (t_idx < length)[:, None]
        alpha_new = jnp.where(m, nxt, alpha)
        return alpha_new, best_prev

    ts = jnp.arange(1, t)
    alpha, backptr = jax.lax.scan(
        step, alpha0, (jnp.swapaxes(emission, 0, 1)[1:], ts)
    )  # backptr [T-1, B, N]

    # add end weights at each sequence's true last step: emulate by adding b
    # to alpha (alpha holds the last valid step's scores after masking)
    alpha = alpha + bvec[None, :]
    last_tag = jnp.argmax(alpha, axis=1)  # [B]

    def back(tag, inp):
        bp, t_idx = inp  # [B, N], scalar (time t_idx, pointer into t_idx+1)
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # only follow pointers within the valid region
        tag_new = jnp.where(t_idx + 1 < length, prev, tag)
        return tag_new, tag_new

    # walk backwards from t-2 .. 0 emitting the tag at each position
    _, tags_rev = jax.lax.scan(
        back, last_tag, (jnp.flip(backptr, 0), jnp.flip(ts - 1, 0))
    )
    path = jnp.concatenate(
        [jnp.flip(tags_rev, 0), last_tag[None]], axis=0
    )  # [T, B] -- position t holds tag chosen at t... need realign
    path = jnp.swapaxes(path, 0, 1)  # [B, T]
    pos = jnp.arange(t)[None, :]
    path = jnp.where(pos < length[:, None], path, 0)
    if ins.get("Label"):
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label[..., 0]
        out = (path == label.astype(path.dtype)).astype(jnp.int32)
        out = jnp.where(pos < length[:, None], out, 0)
        return {"ViterbiPath": [out]}
    return {"ViterbiPath": [path.astype(jnp.int32)]}


@register("chunk_eval", no_grad_inputs=("Inference", "Label", "Length"))
def _chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 for IOB/IOE/IOBES tagging
    (chunk_eval_op.cc). Padded [B, T] int tags + Length.

    Chunk identity = (start position, type). A chunk boundary is detected
    from the tag scheme; implemented vectorized for the common IOB scheme
    with num_chunk_types types: tag = type * tag_multiplier + {B=0, I=1}.
    """
    inference = ins["Inference"][0]
    label = ins["Label"][0]
    if inference.ndim == 3:
        inference = inference[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    b, t = inference.shape
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((b,), t, jnp.int32)
    scheme = attrs.get("chunk_scheme", "IOB")
    num_types = attrs.get("num_chunk_types", 1)
    excluded = attrs.get("excluded_chunk_types", []) or []
    assert scheme == "IOB", "chunk_eval: IOB scheme supported"
    ntag = 2  # B, I

    def starts_types(tags, length):
        pos = jnp.arange(t)[None, :]
        valid = pos < length[:, None]
        typ = tags // ntag
        sub = tags % ntag  # 0=B, 1=I
        prev_typ = jnp.concatenate([jnp.full((b, 1), -1, typ.dtype), typ[:, :-1]], 1)
        prev_sub = jnp.concatenate([jnp.full((b, 1), -1, sub.dtype), sub[:, :-1]], 1)
        outside = tags >= num_types * ntag  # O tag encoded past the range
        prev_outside = jnp.concatenate(
            [jnp.ones((b, 1), jnp.bool_), outside[:, :-1]], 1
        )
        is_start = (~outside) & (
            (sub == 0) | prev_outside | (prev_typ != typ)
        )
        for e in excluded:
            is_start = is_start & (typ != e)
        return is_start & valid, typ, outside

    inf_start, inf_typ, inf_out = starts_types(inference.astype(jnp.int32), length)
    lab_start, lab_typ, lab_out = starts_types(label.astype(jnp.int32), length)

    # chunk end mask: position where chunk continues no further
    def ends(tags_start, outside, length):
        pos = jnp.arange(t)[None, :]
        valid = pos < length[:, None]
        nxt_start = jnp.concatenate(
            [tags_start[:, 1:], jnp.ones((b, 1), jnp.bool_)], 1
        )
        nxt_outside = jnp.concatenate(
            [outside[:, 1:], jnp.ones((b, 1), jnp.bool_)], 1
        )
        nxt_invalid = jnp.concatenate(
            [~valid[:, 1:], jnp.ones((b, 1), jnp.bool_)], 1
        )
        return (~outside) & valid & (nxt_start | nxt_outside | nxt_invalid)

    inf_end = ends(inf_start, inf_out, length)
    lab_end = ends(lab_start, lab_out, length)

    num_inf = jnp.sum(inf_start)
    num_lab = jnp.sum(lab_start)
    # a correct chunk: same start, same end span and same type. Identify
    # chunks by (start_pos); correct if inf and lab both start here with the
    # same type and their ends match at the same position.
    # compute end position per start: cumulative trick — for vectorization,
    # use segment alignment: start positions align iff both start masks set.
    both_start = inf_start & lab_start & (inf_typ == lab_typ)
    # propagate "still matching" until both end: a chunk matches iff between
    # start and end the start masks don't diverge. Simplify: chunk spans are
    # delimited by start/end masks; ends must coincide.
    # scan over time computing "open matched chunk" state
    def match_scan(carry, xs):
        open_m, count = carry
        bs, ie, le, inext, lnext = xs
        open_m = jnp.where(bs, True, open_m)
        # divergence: one ends but not the other
        diverge = (ie ^ le) | (inext ^ lnext)
        closed_ok = open_m & ie & le
        count = count + jnp.sum(closed_ok.astype(jnp.int32))
        open_m = jnp.where(ie | le | diverge, False, open_m)
        return (open_m, count), None

    inf_start_t = jnp.swapaxes(inf_start, 0, 1)
    (_, num_correct), _ = jax.lax.scan(
        match_scan,
        (jnp.zeros((b,), jnp.bool_), jnp.int32(0)),
        (
            jnp.swapaxes(both_start, 0, 1),
            jnp.swapaxes(inf_end, 0, 1),
            jnp.swapaxes(lab_end, 0, 1),
            jnp.swapaxes(inf_start, 0, 1),
            jnp.swapaxes(lab_start, 0, 1),
        ),
    )
    num_inf_f = num_inf.astype(jnp.float32)
    num_lab_f = num_lab.astype(jnp.float32)
    num_cor_f = num_correct.astype(jnp.float32)
    precision = jnp.where(num_inf_f > 0, num_cor_f / jnp.maximum(num_inf_f, 1), 0.0)
    recall = jnp.where(num_lab_f > 0, num_cor_f / jnp.maximum(num_lab_f, 1), 0.0)
    f1 = jnp.where(
        precision + recall > 0, 2 * precision * recall / jnp.maximum(precision + recall, 1e-12), 0.0
    )
    return {
        "Precision": [precision],
        "Recall": [recall],
        "F1-Score": [f1],
        "NumInferChunks": [num_inf.astype(jnp.int32)],
        "NumLabelChunks": [num_lab.astype(jnp.int32)],
        "NumCorrectChunks": [num_correct],
    }
