"""Math / elementwise / reduction / activation op lowerings.

TPU-native re-expression of the reference's ``paddle/fluid/operators/``
elementwise_*, activation, reduce_ops, matmul/mul, softmax and loss ops: each
is one pure JAX rule that XLA fuses into neighboring ops (replacing the
hand-fused mkldnn/cudnn kernels and ``math/`` functor library).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register
from .common import bcast_y, jdt


# ---------------------------------------------------------------------------
# elementwise binary ops (operators/elementwise/*)
# ---------------------------------------------------------------------------
def _elementwise(fn):
    def lower(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        yb = bcast_y(x, y, attrs.get("axis", -1))
        out = fn(x, yb)
        scale = attrs.get("scale", None)
        if scale is not None and scale != 1.0:
            out = out * scale
        return {"Out": [out]}

    return lower


for name, fn in [
    ("elementwise_add", jnp.add),
    ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply),
    ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum),
    ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
    ("elementwise_mod", jnp.mod),
    ("elementwise_floordiv", jnp.floor_divide),
]:
    register(name)(_elementwise(fn))


# ---------------------------------------------------------------------------
# comparison / logical (operators/controlflow/compare_op.cc, logical_op.cc)
# ---------------------------------------------------------------------------
def _compare(fn):
    def lower(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        return {"Out": [fn(x, bcast_y(x, y, attrs.get("axis", -1)))]}

    return lower


for name, fn in [
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
]:
    register(name, no_grad_inputs=("X", "Y"))(_compare(fn))


@register("logical_and", no_grad_inputs=("X", "Y"))
def _logical_and(ctx, ins, attrs):
    return {"Out": [jnp.logical_and(ins["X"][0], ins["Y"][0])]}


@register("logical_or", no_grad_inputs=("X", "Y"))
def _logical_or(ctx, ins, attrs):
    return {"Out": [jnp.logical_or(ins["X"][0], ins["Y"][0])]}


@register("logical_not", no_grad_inputs=("X",))
def _logical_not(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}


@register("logical_xor", no_grad_inputs=("X", "Y"))
def _logical_xor(ctx, ins, attrs):
    return {"Out": [jnp.logical_xor(ins["X"][0], ins["Y"][0])]}


# ---------------------------------------------------------------------------
# activations (operators/activation_op.*)
# ---------------------------------------------------------------------------
def _act(fn):
    def lower(ctx, ins, attrs):
        return {"Out": [fn(ins["X"][0], attrs)]}

    return lower


_ACTS = {
    "relu": lambda x, a: jnp.maximum(x, 0),
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "rsqrt": lambda x, a: jax.lax.rsqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "cos": lambda x, a: jnp.cos(x),
    "sin": lambda x, a: jnp.sin(x),
    "exp": lambda x, a: jnp.exp(x),
    "log": lambda x, a: jnp.log(x),
    "square": lambda x, a: jnp.square(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: x / (1 + jnp.abs(x)),
    "relu6": lambda x, a: jnp.clip(x, 0, a.get("threshold", 6.0)),
    "leaky_relu": lambda x, a: jnp.where(x > 0, x, a.get("alpha", 0.02) * x),
    "elu": lambda x, a: jnp.where(x > 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)),
    "gelu": lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0, 1
    ),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
    "soft_relu": lambda x, a: jnp.log(
        1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))
    ),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * x),
    "thresholded_relu": lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
    "hard_shrink": lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "sign": lambda x, a: jnp.sign(x),
    "erf": lambda x, a: jax.lax.erf(x),
}
for name, fn in _ACTS.items():
    register(name)(_act(fn))


@register("prelu")
def _prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@register("pow")
def _pow(ctx, ins, attrs):
    return {"Out": [jnp.power(ins["X"][0], attrs.get("factor", 1.0))]}


@register("scale", handles_selected_rows=True)
def _scale(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows

    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if isinstance(x, SelectedRows):
        if b:  # a bias densifies by definition
            x = x.densify()
        else:
            return {"Out": [x.scaled(s)]}
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


@register("clip")
def _clip(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs["min"], attrs["max"])]}


@register("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [jnp.where(norm > max_norm, x * (max_norm / norm), x)]}


@register("isfinite", no_grad_inputs=("X",))
def _isfinite(ctx, ins, attrs):
    # reference isfinite reduces over all inputs to a single bool
    ok = jnp.array(True)
    for x in ins["X"]:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return {"Out": [ok]}


@register("has_inf", no_grad_inputs=("X",))
def _has_inf(ctx, ins, attrs):
    """isfinite_op.cc OverflowOp family: any(isinf) over all inputs."""
    bad = jnp.array(False)
    for x in ins["X"]:
        bad = jnp.logical_or(bad, jnp.any(jnp.isinf(x)))
    return {"Out": [bad]}


@register("has_nan", no_grad_inputs=("X",))
def _has_nan(ctx, ins, attrs):
    bad = jnp.array(False)
    for x in ins["X"]:
        bad = jnp.logical_or(bad, jnp.any(jnp.isnan(x)))
    return {"Out": [bad]}


# ---------------------------------------------------------------------------
# matmul family (operators/mul_op.cc, matmul_op.cc)
# ---------------------------------------------------------------------------
def _flatten2(x, ncol):
    lead = 1
    for d in x.shape[:ncol]:
        lead *= d
    rest = 1
    for d in x.shape[ncol:]:
        rest *= d
    return x.reshape(lead, rest)


@register("mul")
def _mul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = _flatten2(x, xn)
    y2 = _flatten2(y, yn)
    out = x2 @ y2
    out_shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    return {"Out": [out.reshape(out_shape)]}


@register("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :] if not tx else x[:, None]
    if y.ndim == 1:
        y = y[:, None] if not ty else y[None, :]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register("dot")
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}


# ---------------------------------------------------------------------------
# reductions (operators/reduce_ops/*)
# ---------------------------------------------------------------------------
def _reduce(fn):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        if attrs.get("reduce_all", False):
            axis = None
        else:
            dim = attrs.get("dim", [0])
            axis = tuple(d % x.ndim for d in (dim if isinstance(dim, (list, tuple)) else [dim]))
        out = fn(x, axis=axis, keepdims=attrs.get("keep_dim", False))
        return {"Out": [out]}

    return lower


for name, fn in [
    ("reduce_sum", jnp.sum),
    ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max),
    ("reduce_min", jnp.min),
    ("reduce_prod", jnp.prod),
]:
    register(name)(_reduce(fn))


@register("mean")
def _mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(ins["X"][0]).reshape(1)]}


@register("sum", handles_selected_rows=True)
def _sum_op(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows, densify_maybe

    xs = ins["X"]
    if xs and all(isinstance(x, SelectedRows) for x in xs):
        # grad fan-in of sparse grads stays sparse: concatenate the row
        # sets (duplicates are fine — consumers merge or scatter-add)
        rows = jnp.concatenate([x.rows for x in xs])
        vals = jnp.concatenate([x.value for x in xs])
        return {"Out": [SelectedRows(rows, vals, xs[0].height)]}
    xs = [densify_maybe(x) for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.square(ins["X"][0])).reshape(1)]}


@register("frobenius_norm")
def _frobenius_norm(ctx, ins, attrs):
    return {"Out": [jnp.sqrt(jnp.sum(jnp.square(ins["X"][0]))).reshape(1)]}


@register("norm")
def _norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


# ---------------------------------------------------------------------------
# softmax & losses (operators/softmax_op, cross_entropy_op,
# softmax_with_cross_entropy_op)
# ---------------------------------------------------------------------------
@register("softmax")
def _softmax(ctx, ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=axis)]}


@register("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


def _take_label(x, label):
    """x[..., label] along last axis; label shape [..., 1] int."""
    lbl = label.astype(jnp.int32)
    if lbl.ndim == x.ndim:
        lbl = lbl[..., 0]
    return jnp.take_along_axis(x, lbl[..., None], axis=-1)


@register("cross_entropy", no_grad_inputs=("Label",))
def _cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.clip(x, 1e-20, None)), axis=-1, keepdims=True)
    else:
        p = _take_label(x, label)
        loss = -jnp.log(jnp.clip(p, 1e-20, None))
    return {"Y": [loss]}


@register("softmax_with_cross_entropy", no_grad_inputs=("Label",))
def _softmax_xent(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    from .pallas_kernels import fused_softmax_xent, use_pallas

    if (
        use_pallas()
        and not attrs.get("soft_label", False)
        and attrs.get("ignore_index", -100) < 0
        and logits.ndim == 2
    ):
        # fused logsumexp+gather kernel; Softmax output stays lazy (XLA
        # computes it only if a consumer asks)
        loss = fused_softmax_xent(
            logits, label.reshape(-1).astype(jnp.int32)
        ).astype(logits.dtype)
        return {
            "Softmax": [jax.nn.softmax(logits, axis=-1)],
            "Loss": [loss],
        }
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lp = _take_label(logp, label)
        if attrs.get("ignore_index", -100) >= 0:
            ig = attrs["ignore_index"]
            lbl = label if label.ndim == logits.ndim else label[..., None]
            mask = (lbl.astype(jnp.int32) != ig).astype(logp.dtype)
            lp = lp * mask
        loss = -lp
    return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}


@register("smooth_label_xent", no_grad_inputs=("Label",))
def _smooth_label_xent(ctx, ins, attrs):
    """Label-smoothed softmax cross-entropy in closed form — the fused
    target of smooth_label_xent_fuse_pass (one_hot -> label_smooth ->
    softmax_with_cross_entropy(soft_label), the reference training-loss
    idiom: label_smooth_op.cc + softmax_with_cross_entropy_op.cc).

    With s = (1-eps)*onehot(y) + eps/V (uniform prior) and
    logp = logits - lse:

        -sum(s * logp) = (1-eps)*(lse - logits[y]) + eps*(lse - mean(logits))

    so NO [N, V] one-hot / smoothed-label / log-softmax array is ever
    materialized in HBM — at transformer-base bench config that is three
    ~1.3 GB f32 arrays per step direction.  f32 internals regardless of
    the (possibly bf16) logits dtype; grads via the generic vjp."""
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    eps = float(attrs.get("epsilon", 0.0))
    lg = logits.astype(jnp.float32)
    v = lg.shape[-1]
    lse = jax.scipy.special.logsumexp(lg, axis=-1, keepdims=True)
    ly = _take_label(lg, label)
    # out-of-range labels (e.g. -1 padding ids): one_hot emitted an
    # all-zero row there, so the unfused loss is just the smoothing term
    # — match it exactly instead of take_along_axis's wrap/clamp gather
    lbl = label.astype(jnp.int32)
    if lbl.ndim == lg.ndim:
        lbl = lbl[..., 0]
    valid = ((lbl >= 0) & (lbl < v))[..., None]
    smooth_term = (
        eps * (lse - jnp.mean(lg, axis=-1, keepdims=True)) if eps
        else jnp.zeros_like(lse)
    )
    loss = jnp.where(valid, (1.0 - eps) * (lse - ly), 0.0) + smooth_term
    return {"Loss": [loss.astype(logits.dtype)]}


@register("fused_linear_xent", no_grad_inputs=("Label",))
def _fused_linear_xent_op(ctx, ins, attrs):
    """Logits-free projected cross entropy — the fused target of
    linear_xent_fuse_pass (the final [H, V] projection folded INTO
    softmax_with_cross_entropy / smooth_label_xent).  Inputs: X
    [..., H] hidden states, W [H, V] (or [V, H] with transpose_w, the
    tied-embedding form), Label [..., 1] int.  Under FLAGS_use_pallas
    the [R, V] f32 logits tensor never materializes in HBM: the
    forward streams vocab tiles through an online logsumexp and the
    backward recomputes per-tile softmax against W
    (pallas_kernels.fused_linear_xent); the dense fallback is the
    closed-form XLA reference.  Label convention matches
    smooth_label_xent: out-of-range labels contribute the smoothing
    term only.

    transpose_w (the tied-embedding x @ W^T form) materializes a
    physical [H, V] transposed copy of W per step — the kernels read
    [H, V]-layout tiles; a weights-sized copy (~150 MB for gpt2) is
    still far below the [R, V] logits the fusion eliminates (several
    GB at bench config), but a [V, H]-layout kernel variant would
    remove it (documented known limit)."""
    from .pallas_kernels import (
        _linear_xent_dense,
        fused_linear_xent,
        use_pallas,
    )

    x = ins["X"][0]
    w = ins["W"][0]
    label = ins["Label"][0]
    eps = float(attrs.get("epsilon", 0.0))
    if attrs.get("transpose_w", False):
        w = w.T
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    lbl = label.reshape(-1).astype(jnp.int32)
    if use_pallas():
        from .spmd_epilogue import spmd_linear_xent

        loss2 = spmd_linear_xent(ctx, x2, w, lbl, eps,
                                 bool(attrs.get("transpose_w", False)))
        if loss2 is None:
            loss2 = fused_linear_xent(x2, w, lbl, eps)
    else:
        loss2 = _linear_xent_dense(x2, w, lbl, eps)
    loss = loss2.reshape(tuple(x.shape[:-1]) + (1,)).astype(x.dtype)
    return {"Loss": [loss]}


@register("sigmoid_cross_entropy_with_logits", no_grad_inputs=("Label",))
def _sigmoid_xent(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    mask = (label != ignore).astype(x.dtype)
    return {"Out": [loss * mask]}


@register("square_error_cost", no_grad_inputs=("Y",))
def _square_error(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.square(x - y)]}


@register("smooth_l1_loss", no_grad_inputs=("Y", "InsideWeight", "OutsideWeight"))
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * ad * ad, ad - 0.5 / s2)
    if ins.get("OutsideWeight"):
        loss = loss * ins["OutsideWeight"][0]
    loss = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [loss], "Diff": [diff]}


@register("huber_loss", no_grad_inputs=("Y",))
def _huber(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register("label_smooth", no_grad_inputs=("PriorDist",))
def _label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    prior = ins.get("PriorDist", [None])[0]
    if prior is None:
        prior = 1.0 / x.shape[-1]
    return {"Out": [(1 - eps) * x + eps * prior]}


# ---------------------------------------------------------------------------
# metrics (operators/metrics/*)
# ---------------------------------------------------------------------------
@register("top_k", no_grad_inputs=("X",))
def _top_k(ctx, ins, attrs):
    x = ins["X"][0]
    k = attrs["k"]
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int32)]}


@register("accuracy", no_grad_inputs=("Out", "Indices", "Label"))
def _accuracy(ctx, ins, attrs):
    idx = ins["Indices"][0]
    label = ins["Label"][0]
    if label.ndim < idx.ndim:
        label = label[..., None]
    correct = jnp.any(idx == label.astype(idx.dtype), axis=-1)
    total = correct.shape[0]
    num_correct = jnp.sum(correct.astype(jnp.int32))
    acc = num_correct.astype(jnp.float32) / total
    return {
        "Accuracy": [acc.reshape(1)],
        "Correct": [num_correct.reshape(1)],
        "Total": [jnp.array([total], jnp.int32)],
    }


@register("arg_max", no_grad_inputs=("X",))
def _arg_max(ctx, ins, attrs):
    return {"Out": [jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int32)]}


@register("arg_min", no_grad_inputs=("X",))
def _arg_min(ctx, ins, attrs):
    return {"Out": [jnp.argmin(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int32)]}


@register("argsort", no_grad_inputs=("X",))
def _argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": [jnp.sort(x, axis=axis)], "Indices": [idx.astype(jnp.int32)]}


@register("cumsum")
def _cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if attrs.get("exclusive", False):
        out = out - x
    return {"Out": [out]}


@register("maximum")
def _maximum(ctx, ins, attrs):
    return {"Out": [jnp.maximum(ins["X"][0], ins["Y"][0])]}


@register("minimum")
def _minimum(ctx, ins, attrs):
    return {"Out": [jnp.minimum(ins["X"][0], ins["Y"][0])]}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py): registered alongside the
# lowerings so the shape/dtype contract and the kernel live in one file
# ---------------------------------------------------------------------------
from ..analysis.infer import (  # noqa: E402
    InferError,
    VarInfo,
    elementwise_shape,
    register_infer,
    same_as,
    same_dtype,
    slot_info as _i,
)


def _ew_infer(op, ins):
    x, y = _i(ins, "X"), _i(ins, "Y")
    shape = elementwise_shape(x, y, op.attrs.get("axis", -1))
    return {"Out": [VarInfo(shape, same_dtype(x, y))]}


for _name in (
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "elementwise_floordiv",
    "maximum", "minimum",
):
    register_infer(_name, req_ins=("X", "Y"))(_ew_infer)


def _cmp_infer(op, ins):
    x, y = _i(ins, "X"), _i(ins, "Y")
    shape = elementwise_shape(x, y, op.attrs.get("axis", -1))
    return {"Out": [VarInfo(shape, "bool")]}


for _name in (
    "less_than", "less_equal", "greater_than", "greater_equal",
    "equal", "not_equal", "logical_and", "logical_or", "logical_xor",
):
    register_infer(_name, req_ins=("X", "Y"))(_cmp_infer)
register_infer("logical_not", req_ins=("X",))(
    lambda op, ins: {"Out": [VarInfo(
        _i(ins, "X").shape if _i(ins, "X") else None, "bool")]})

for _name in tuple(_ACTS) + (
    "pow", "clip", "clip_by_norm", "softmax", "log_softmax", "cumsum",
):
    register_infer(_name, req_ins=("X",))(same_as("X"))
register_infer("scale", req_ins=("X",))(same_as("X"))
register_infer("prelu", req_ins=("X", "Alpha"))(same_as("X"))


def _reduce_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {"Out": [VarInfo(None, x.dtype if x else None)]}
    nd = len(x.shape)
    if op.attrs.get("reduce_all", False):
        axes = set(range(nd))
    else:
        dim = op.attrs.get("dim", [0])
        dim = dim if isinstance(dim, (list, tuple)) else [dim]
        axes = set(int(d) % nd for d in dim)
    keep = bool(op.attrs.get("keep_dim", False))
    shape = tuple(
        1 if (i in axes and keep) else d
        for i, d in enumerate(x.shape)
        if keep or i not in axes)
    return {"Out": [VarInfo(shape, x.dtype)]}


for _name in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
              "reduce_prod"):
    register_infer(_name, req_ins=("X",))(_reduce_infer)


@register_infer("mean", req_ins=("X",))
def _mean_infer(op, ins):
    x = _i(ins, "X")
    return {"Out": [VarInfo((1,), x.dtype if x else None)]}


@register_infer("sum", req_ins=("X",))
def _sum_infer(op, ins):
    x = _i(ins, "X")
    if x is None:
        return {}
    return {"Out": [VarInfo(x.shape, x.dtype)]}


def _mm_flat(shape, k):
    lead, tail = shape[:k], shape[k:]
    from ..analysis.infer import numel_known

    return numel_known(lead), numel_known(tail)


@register_infer("mul", req_ins=("X", "Y"))
def _mul_infer(op, ins):
    x, y = _i(ins, "X"), _i(ins, "Y")
    if x is None or y is None or x.shape is None or y.shape is None:
        return {"Out": [VarInfo(None, same_dtype(x, y))]}
    xn = int(op.attrs.get("x_num_col_dims", 1))
    yn = int(op.attrs.get("y_num_col_dims", 1))
    if not (0 < xn < len(x.shape) + 1 and 0 < yn < len(y.shape) + 1):
        raise InferError(
            "mul num_col_dims (%d, %d) out of range for ranks (%d, %d)"
            % (xn, yn, len(x.shape), len(y.shape)))
    _, xk = _mm_flat(x.shape, xn)
    yk, _ = _mm_flat(y.shape, yn)
    if xk is not None and yk is not None and xk != yk:
        raise InferError(
            "mul contraction mismatch: X%s flattens to K=%d but Y%s "
            "expects K=%d" % (x.shape, xk, y.shape, yk))
    shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    return {"Out": [VarInfo(shape, same_dtype(x, y))]}


@register_infer("matmul", req_ins=("X", "Y"))
def _matmul_infer(op, ins):
    from ..analysis.infer import broadcast_shapes

    x, y = _i(ins, "X"), _i(ins, "Y")
    if x is None or y is None or x.shape is None or y.shape is None:
        return {"Out": [VarInfo(None, same_dtype(x, y))]}
    xs, ys = list(x.shape), list(y.shape)
    tx = bool(op.attrs.get("transpose_X", False))
    ty = bool(op.attrs.get("transpose_Y", False))
    if len(xs) == 1:
        xs = [1, xs[0]] if not tx else [xs[0], 1]
    if len(ys) == 1:
        ys = [ys[0], 1] if not ty else [1, ys[0]]
    if tx:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if ty:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if xs[-1] >= 0 and ys[-2] >= 0 and xs[-1] != ys[-2]:
        raise InferError(
            "matmul contraction mismatch: %s @ %s (transpose_X=%s, "
            "transpose_Y=%s)" % (x.shape, y.shape, tx, ty))
    batch = broadcast_shapes(xs[:-2], ys[:-2], "matmul batch")
    shape = None if batch is None else tuple(batch) + (xs[-2], ys[-1])
    return {"Out": [VarInfo(shape, same_dtype(x, y))]}


@register_infer("dot", req_ins=("X", "Y"))
def _dot_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    return {"Out": [VarInfo(x.shape[:-1] + (1,), x.dtype)]}


def _rowloss_shape(x):
    if x is None or x.shape is None:
        return None
    return x.shape[:-1] + (1,)


@register_infer("cross_entropy", req_ins=("X", "Label"), req_outs=("Y",))
def _xent_infer(op, ins):
    x = _i(ins, "X")
    return {"Y": [VarInfo(_rowloss_shape(x), x.dtype if x else None)]}


@register_infer("softmax_with_cross_entropy", req_ins=("Logits", "Label"),
                req_outs=("Loss",))
def _sxent_infer(op, ins):
    x = _i(ins, "Logits")
    return {
        "Softmax": [VarInfo(x.shape if x else None, x.dtype if x else None)],
        "Loss": [VarInfo(_rowloss_shape(x), x.dtype if x else None)],
    }


@register_infer("smooth_label_xent", req_ins=("Logits", "Label"),
                req_outs=("Loss",))
def _slx_infer(op, ins):
    x = _i(ins, "Logits")
    return {"Loss": [VarInfo(_rowloss_shape(x), x.dtype if x else None)]}


@register_infer("fused_linear_xent", req_ins=("X", "W", "Label"),
                req_outs=("Loss",))
def _flx_infer(op, ins):
    x, w = _i(ins, "X"), _i(ins, "W")
    if x is None or x.shape is None:
        return {}
    if (w is not None and w.shape is not None and len(w.shape) == 2
            and x.shape[-1] >= 0):
        h = w.shape[1] if op.attrs.get("transpose_w", False) else w.shape[0]
        if h >= 0 and x.shape[-1] != h:
            raise InferError(
                "fused_linear_xent hidden-dim mismatch: X%s vs W%s "
                "(transpose_w=%s)" % (x.shape, w.shape,
                                      bool(op.attrs.get("transpose_w"))))
    return {"Loss": [VarInfo(_rowloss_shape(x), x.dtype)]}


@register_infer("square_error_cost", req_ins=("X", "Y"))
def _sec_infer(op, ins):
    x = _i(ins, "X")
    return {"Out": [VarInfo(x.shape if x else None, x.dtype if x else None)]}


@register_infer("top_k", req_ins=("X",), req_outs=("Out", "Indices"))
def _topk_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    k = int(op.attrs.get("k", 1))
    shape = x.shape[:-1] + (k,)
    return {"Out": [VarInfo(shape, x.dtype)],
            "Indices": [VarInfo(shape, None)]}


@register_infer("accuracy", req_ins=("Indices", "Label"),
                req_outs=("Accuracy",))
def _acc_infer(op, ins):
    return {"Accuracy": [VarInfo((1,), "float32")]}


def _arg_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    nd = len(x.shape)
    ax = int(op.attrs.get("axis", -1)) % nd
    keep = bool(op.attrs.get("keepdims", False))
    shape = tuple(
        1 if (i == ax and keep) else d
        for i, d in enumerate(x.shape) if keep or i != ax)
    return {"Out": [VarInfo(shape, None)]}


register_infer("arg_max", req_ins=("X",))(_arg_infer)
register_infer("arg_min", req_ins=("X",))(_arg_infer)
