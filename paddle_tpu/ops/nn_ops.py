"""Neural-net op lowerings: conv, pool, norms, dropout, rnn blocks.

TPU-native replacements for the reference's cudnn/mkldnn-backed kernels
(``operators/conv_op.*``, ``pool_op.*``, ``batch_norm_op.*``,
``layer_norm_op.*``, ``dropout_op.*``, ``lstm_op.*``, ``gru_op.*``): convs
map to ``lax.conv_general_dilated`` (MXU), recurrences to ``lax.scan``
(compiled control flow instead of the reference's per-step StepScopes
interpreter), and gradients fall out of ``jax.vjp`` — including scan-based
RNNs.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import current_microbatch_rows, register
from .common import jdt


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


# ---------------------------------------------------------------------------
# convolution family
# ---------------------------------------------------------------------------
def _conv2d_impl(x, w, attrs, groups=None):
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = groups if groups is not None else attrs.get("groups", 1) or 1
    fmt = attrs.get("data_format", "NCHW")  # nhwc_layout_pass sets NHWC
    pad = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=pad,
        rhs_dilation=dilations,
        dimension_numbers=(fmt, "OIHW", fmt),
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None,
    )


def _bias_shape(attrs, ndim=4):
    shape = [1] * ndim
    shape[1 if attrs.get("data_format", "NCHW") == "NCHW" else ndim - 1] = -1
    return shape


@register("conv2d")
def _conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    out = _conv2d_impl(x, w, attrs)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(_bias_shape(attrs))
    if attrs.get("fuse_relu"):  # fuse_relu_into_conv_pass epilogue
        out = jnp.maximum(out, 0)
    return {"Output": [out]}


@register("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    ch = x.shape[1 if attrs.get("data_format", "NCHW") == "NCHW" else -1]
    out = _conv2d_impl(x, w, attrs, groups=ch)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(_bias_shape(attrs))
    return {"Output": [out]}


@register("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    # paddle semantics: out = (H-1)*s - 2p + k_eff.  jax applies `padding`
    # to the stride-dilated input of a plain conv with the flipped kernel,
    # so each side needs k_eff - 1 - p
    k_eff = [dilations[i] * (w.shape[2 + i] - 1) + 1 for i in range(2)]
    pad = [(k_eff[i] - 1 - paddings[i],) * 2 for i in range(2)]

    # w layout: [in_c, out_c/groups, kh, kw] (paddle conv_transpose filter);
    # with transpose_kernel=True jax SWAPS the I/O labels, so the in_c dim
    # must be labeled 'O' (it is the contraction side of the transposed
    # conv); lax.conv_transpose has no group support, so groups unroll
    def one(xg, wg):
        return jax.lax.conv_transpose(
            xg,
            wg,
            strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True,
        )

    if groups == 1:
        out = one(x, w)
    else:
        cin = x.shape[1] // groups
        outs = [
            one(x[:, g * cin : (g + 1) * cin], w[g * cin : (g + 1) * cin])
            for g in range(groups)
        ]
        out = jnp.concatenate(outs, axis=1)
    return {"Output": [out]}


@register("conv3d")
def _conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = attrs.get("strides", [1, 1, 1])
    paddings = attrs.get("paddings", [0, 0, 0])
    dilations = attrs.get("dilations", [1, 1, 1])
    pad = [(p, p) for p in paddings]
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=pad,
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1) or 1,
    )
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# pooling (operators/pool_op.*)
# ---------------------------------------------------------------------------
@register("pool2d")
def _pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    sp = (1, 2) if nhwc else (2, 3)  # spatial axes
    if attrs.get("global_pooling", False) or attrs.get("adaptive", False) and list(
        attrs.get("ksize")
    ) == [1, 1]:
        if ptype == "max":
            out = jnp.max(x, axis=sp, keepdims=True)
        else:
            out = jnp.mean(x, axis=sp, keepdims=True)
        return {"Out": [out]}

    def _full(h, w_):
        # place the spatial (h, w) values on the spatial axes, 1 elsewhere
        full = [1, 1, 1, 1]
        full[sp[0]], full[sp[1]] = h, w_
        return tuple(full)

    window = _full(ksize[0], ksize[1])
    strides_full = _full(strides[0], strides[1])
    extra = [0, 0]
    if attrs.get("ceil_mode", False):
        # pad right/bottom so the window count rounds up
        for i, (dim, k, s, p) in enumerate(
            zip((x.shape[sp[0]], x.shape[sp[1]]), ksize, strides, paddings)
        ):
            total = dim + 2 * p
            rem = (total - k) % s
            extra[i] = (s - rem) % s if rem else 0
    pads = [(0, 0)] * 4
    pads[sp[0]] = (paddings[0], paddings[0] + extra[0])
    pads[sp[1]] = (paddings[1], paddings[1] + extra[1])
    pads = tuple(pads)
    any_padding = any(pads[a] != (0, 0) for a in sp)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides_full, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_full, pads)
        if attrs.get("exclusive", True) and any_padding:
            # divide by the count of valid (unpadded) elements per window —
            # covers both explicit padding and ceil_mode's implicit padding
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides_full, pads)
            out = summed / counts
        else:
            out = summed / (ksize[0] * ksize[1])
    return {"Out": [out]}


@register("adaptive_pool2d")
def _adaptive_pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    oh, ow = attrs["pooling_size"] if "pooling_size" in attrs else attrs["ksize"]
    n, c, h, w = x.shape
    assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible dims"
    x = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if attrs.get("pooling_type", "avg") == "max":
        return {"Out": [jnp.max(x, axis=(3, 5))]}
    return {"Out": [jnp.mean(x, axis=(3, 5))]}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
@register("batch_norm", no_grad_inputs=("Mean", "Variance"))
def _batch_norm(ctx, ins, attrs):
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    is_test = attrs.get("is_test", False) or attrs.get("use_global_stats", False) or ctx.is_test
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    # statistics and normalization run in f32 even for bf16 activations
    # (the AMP trunk keeps x bf16 in HBM; the f32 upcast fuses into the
    # same loop, so the reduce accumulates at full precision for free) —
    # Y comes back in x's dtype, running stats/Saved* stay f32
    xs = x if x.dtype == jnp.float32 else x.astype(jnp.float32)
    if is_test:
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        mean_out, var_out = mean, var
    else:
        use_mean = jnp.mean(xs, axis=red_axes)
        use_var = jnp.var(xs, axis=red_axes)
        saved_mean, saved_var = use_mean, use_var
        mean_out = momentum * mean + (1 - momentum) * use_mean
        var_out = momentum * var + (1 - momentum) * use_var
        # running stats are pure state updates, not differentiated through
        mean_out = jax.lax.stop_gradient(mean_out)
        var_out = jax.lax.stop_gradient(var_out)

    inv = jax.lax.rsqrt(use_var + eps)
    y = (xs - use_mean.reshape(bshape)) * inv.reshape(bshape) * scale.reshape(
        bshape
    ) + bias.reshape(bshape)
    y = y.astype(x.dtype)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [jax.lax.stop_gradient(inv)],
    }


@register("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = ins["X"][0]
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    # pallas kernel override when the norm is over the last axis only
    # (the transformer case) — FLAGS_use_pallas, library-override analog
    from .pallas_kernels import fused_layer_norm, use_pallas

    if (
        use_pallas()
        and begin == x.ndim - 1
        and ins.get("Scale")
        and ins.get("Bias")
    ):
        h = x.shape[-1]
        x2d = x.reshape(-1, h)
        y = fused_layer_norm(
            x2d, ins["Scale"][0].reshape(h), ins["Bias"][0].reshape(h), eps
        ).reshape(x.shape)
        # stats in f32 regardless of input dtype (same invariant as the
        # fallback path below; the kernel already normalizes in f32)
        xf32 = x.astype(jnp.float32)
        mean = jnp.mean(xf32, axis=-1)
        var = jnp.var(xf32, axis=-1)
        return {
            "Y": [y],
            "Mean": [jax.lax.stop_gradient(mean)],
            "Variance": [jax.lax.stop_gradient(var)],
        }
    # statistics + normalization in f32 regardless of input dtype (bf16
    # inputs under AMP keep f32-quality stats; the upcast fuses into the
    # same loop), Y returned in the input dtype so the op is
    # dtype-transparent for the AMP trunk pass
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = (xf - mean) * inv
    norm_shape = x.shape[begin:]
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(norm_shape).astype(jnp.float32)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(norm_shape).astype(jnp.float32)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [jax.lax.stop_gradient(mean.reshape(mean.shape[:begin]))],
        "Variance": [jax.lax.stop_gradient(var.reshape(var.shape[:begin]))],
    }


@register("group_norm")
def _group_norm(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs.get("groups", 32)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape(n, g, c // g, *x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    shp = [1, c] + [1] * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(shp)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(shp)
    return {"Y": [y], "Mean": [mean.reshape(n, g)], "Variance": [var.reshape(n, g)]}


@register("instance_norm")
def _instance_norm(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    shp = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(shp)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(shp)
    return {"Y": [y]}


@register("l2_normalize")
def _l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


@register("lrn")
def _lrn(ctx, ins, attrs):
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k, alpha, beta = attrs.get("k", 2.0), attrs.get("alpha", 1e-4), attrs.get("beta", 0.75)
    sq = jnp.square(x)
    pad = n // 2
    sq_pad = jnp.pad(sq, ((0, 0), (pad, pad), (0, 0), (0, 0)))
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + sq_pad[:, i : i + x.shape[1]]
    mid = jnp.power(k + alpha * acc, beta)
    return {"Out": [x / mid], "MidOut": [mid]}


# ---------------------------------------------------------------------------
# dropout (operators/dropout_op.*)
# ---------------------------------------------------------------------------
@register("dropout", needs_rng=True)
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    is_test = attrs.get("is_test", False) or ctx.is_test
    if is_test:
        if impl == "upscale_in_train":
            return {"Out": [x], "Mask": [jnp.ones_like(x)]}
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    mb = current_microbatch_rows()
    if mb is not None and x.ndim >= 1:
        # pipeline microbatch: draw the mask over the FULL global batch
        # rows (bit-identical to the unpipelined trace — threefry is
        # counter-based per position) and slice this microbatch's window
        total_rows, row_offset = mb
        keep = jax.random.bernoulli(
            ctx.rng(attrs), 1.0 - p, (total_rows,) + tuple(x.shape[1:])
        )
        keep = jax.lax.dynamic_slice_in_dim(keep, row_offset, x.shape[0], 0)
    else:
        keep = jax.random.bernoulli(ctx.rng(attrs), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0)
    else:
        out = x * mask
    return {"Out": [out], "Mask": [mask]}


# ---------------------------------------------------------------------------
# recurrent blocks: lstm / gru as scan ops
# ---------------------------------------------------------------------------
def _lstm_cell(c_prev, h_prev, gates, forget_bias=0.0):
    i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * jnp.tanh(c_hat)
    h = o * jnp.tanh(c)
    return c, h


@register("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    gates, c_prev = ins["X"][0], ins["C_prev"][0]
    c, h = _lstm_cell(c_prev, None, gates, attrs.get("forget_bias", 0.0))
    return {"C": [c], "H": [h]}


@register("fc")
def _fc(ctx, ins, attrs):
    """Fused fully-connected (fc_op of fc_fuse_pass.cc): mul + bias-add +
    activation in one op.  Under FLAGS_use_pallas the blocked
    matmul-epilogue kernel applies bias + activation to the accumulator
    tile in VMEM (matmul_bias_act); otherwise one MXU matmul with an
    XLA-fused epilogue."""
    from .pallas_kernels import (
        _mm_act,
        matmul_bias_act,
        mm_epilogue_ok,
        use_pallas,
    )

    x, w = ins["Input"][0], ins["W"][0]
    k = int(attrs.get("in_num_col_dims", 1))
    x2 = x.reshape((int(np.prod(x.shape[:k])), -1))
    act = attrs.get("activation_type", "") or ""
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    M, K = x2.shape
    if (
        use_pallas()
        and w.ndim == 2
        and (bias is None or bias.shape[0] == w.shape[-1])
        and mm_epilogue_ok(M, K, w.shape[-1], act)
    ):
        from .spmd_epilogue import spmd_matmul_bias_act

        out = spmd_matmul_bias_act(ctx, x2, w, bias, act)
        if out is None:
            out = matmul_bias_act(x2, w, bias, act)
        return {"Out": [out.reshape(tuple(x.shape[:k]) + (w.shape[-1],))]}
    out = x2 @ w
    out = out.reshape(tuple(x.shape[:k]) + (w.shape[-1],))
    if bias is not None:
        out = out + bias.reshape((1,) * k + (-1,))
    # ONE activation table for both paths (the kernel epilogue's):
    # dense fallback and pallas epilogue can never drift apart
    return {"Out": [_mm_act(out, act)]}


@register("fused_swiglu")
def _fused_swiglu(ctx, ins, attrs):
    """Fused SwiGLU gating (swiglu_fuse_pass target): silu(x @ GateW) *
    (x @ UpW) in one op — the pallas kernel computes both projections of
    a row tile and the gate product in VMEM (matmul_swiglu); the dense
    path is the XLA reference."""
    from .pallas_kernels import (
        _swiglu_dense,
        matmul_swiglu,
        mm_epilogue_ok,
        use_pallas,
    )

    x, wg, wu = ins["X"][0], ins["GateW"][0], ins["UpW"][0]
    k = int(attrs.get("x_num_col_dims", 1))
    x2 = x.reshape((int(np.prod(x.shape[:k])), -1))
    M, K = x2.shape
    N = wg.shape[-1]
    if use_pallas() and mm_epilogue_ok(M, K, N, extra_w=2):
        from .spmd_epilogue import spmd_matmul_swiglu

        out = spmd_matmul_swiglu(ctx, x2, wg, wu)
        if out is None:
            out = matmul_swiglu(x2, wg, wu)
    else:
        out = _swiglu_dense(x2, wg, wu)
    return {"Out": [out.reshape(tuple(x.shape[:k]) + (N,))]}


@register("fused_residual_ln")
def _fused_residual_ln(ctx, ins, attrs):
    """Residual add + layer norm (residual_ln_fuse_pass target): the add
    is the LN kernel's prologue — the sum forms on the row tile in VMEM,
    normalizes in the same pass, and BOTH the sum (the residual stream
    downstream consumers keep reading under its original name) and the
    normalized output write out once.  Stats in f32 like layer_norm."""
    from .pallas_kernels import (
        _add_ln_dense,
        fused_add_layer_norm,
        use_pallas,
    )

    x, y = ins["X"][0], ins["Y"][0]
    eps = attrs.get("epsilon", 1e-5)
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    y2 = y.reshape(-1, h)
    gamma = ins["Scale"][0].reshape(h)
    beta = ins["Bias"][0].reshape(h)
    if use_pallas():
        from .spmd_epilogue import spmd_add_layer_norm

        res = spmd_add_layer_norm(ctx, x2, y2, gamma, beta, eps)
        s2, o2 = res if res is not None else fused_add_layer_norm(
            x2, y2, gamma, beta, eps)
    else:
        s2, o2 = _add_ln_dense(x2, y2, gamma, beta, eps)
    s = s2.reshape(x.shape)
    sf = s.astype(jnp.float32)
    mean = jnp.mean(sf, axis=-1)
    var = jnp.var(sf, axis=-1)
    return {
        "Sum": [s],
        "Y": [o2.reshape(x.shape)],
        "Mean": [jax.lax.stop_gradient(mean)],
        "Variance": [jax.lax.stop_gradient(var)],
    }


@register("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    """fused/fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias + relu
    as one op (seqconv_eltadd_relu_fuse_pass target)."""
    from ..core.registry import get_op

    conv_ins = {"X": ins["X"], "Filter": ins["Filter"]}
    if ins.get("SeqLen"):
        conv_ins["SeqLen"] = ins["SeqLen"]
    out = get_op("sequence_conv").lower(ctx, conv_ins, attrs)["Out"][0]
    out = out + ins["Bias"][0].reshape((1,) * (out.ndim - 1) + (-1,))
    return {"Out": [jnp.maximum(out, 0)]}


@register("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """fused/fusion_seqexpand_concat_fc_op.cc: X[0] is a [B, T, D0]
    sequence; every further X[i] is a per-example [B, Di] vector expanded
    to all T steps; features concat and feed one fc (+ activation)."""
    xs = ins["X"]
    seq = xs[0]
    b, t = seq.shape[0], seq.shape[1]
    parts = [seq] + [
        jnp.broadcast_to(v[:, None, :], (b, t, v.shape[-1])) for v in xs[1:]
    ]
    cat = jnp.concatenate(parts, axis=-1)
    out = cat @ ins["FCWeight"][0]
    if ins.get("FCBias"):
        out = out + ins["FCBias"][0].reshape(1, 1, -1)
    act = attrs.get("fc_activation", "identity")
    fn = {"identity": lambda x: x, "relu": jax.nn.relu, "tanh": jnp.tanh,
          "sigmoid": jax.nn.sigmoid}[act]
    return {"Out": [fn(out)]}


@register("fused_embedding_fc_lstm", no_grad_inputs=("Ids",))
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """fused/fused_embedding_fc_lstm_op.cc capability: embedding lookup +
    input projection + LSTM recurrence as one op
    (embedding_fc_lstm_fuse_pass target).  Inputs: Ids, Embeddings
    [vocab, D], WeightX [D, 4H], WeightH [H, 4H], optional BiasX/Bias,
    optional SeqLen/H0/C0; same outputs as `lstm`."""
    from ..core.registry import get_op

    ids = ins["Ids"][0].astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    from .compat_ops import project_input_maybe

    emb = jnp.take(ins["Embeddings"][0], ids, axis=0)  # [B, T, D]
    xproj = project_input_maybe(dict(ins, Input=[emb]))["Input"][0]
    lstm_ins = {"Input": [xproj], "Weight": ins["WeightH"]}
    for slot in ("Bias", "SeqLen", "H0", "C0"):
        if ins.get(slot):
            lstm_ins[slot] = ins[slot]
    out = get_op("padded_lstm").lower(ctx, lstm_ins, attrs)
    return {
        "Hidden": out["Hidden"],
        "Cell": out["CellSeq"],
        "LastH": out["LastH"],
        "LastC": out["LastC"],
    }


@register("padded_lstm")
def _padded_lstm(ctx, ins, attrs):
    """TPU-native LSTM over padded [batch, time, 4*hidden] projected input.

    Replaces the reference's LoD-reordered `lstm_op` (sequence2batch +
    per-step gemm): here the input projection is done outside as one big
    matmul and the recurrence is a lax.scan over time with a length mask.
    Inputs: Input (projected gates), Weight [hidden, 4*hidden], Bias
    [4*hidden], optional SeqLen [batch], optional H0/C0.
    """
    xproj = ins["Input"][0]  # [B, T, 4H]
    w = ins["Weight"][0]  # [H, 4H]
    b = ins["Bias"][0] if ins.get("Bias") else None
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    bsz, t, h4 = xproj.shape
    hid = h4 // 4
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((bsz, hid), xproj.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((bsz, hid), xproj.dtype)
    is_reverse = attrs.get("is_reverse", False)

    # forward direction: one shared masked recurrence (_lstm_seq_dense,
    # also the fused path's backward recompute — the GRU pattern, no
    # formula triplication), with the VMEM-resident fused kernel
    # (jit_kernel lstm / fusion_lstm slot) when eligible: lane-aligned
    # hidden, working set within VMEM.  Bias folds into the projected
    # gates either way.
    from .pallas_kernels import (
        _interpret,
        _lstm_seq_dense,
        _row_block,
        fused_lstm,
        use_pallas,
    )

    if not is_reverse:
        lens = (
            seq_len.reshape(-1).astype(jnp.int32)
            if seq_len is not None
            else jnp.full((bsz,), t, jnp.int32)
        )
        xg = xproj if b is None else xproj + b.reshape(1, 1, -1)
        lane_ok = hid % (8 if _interpret() else 128) == 0
        blk = _row_block(bsz, 8)
        vmem_bytes = blk * t * (4 + 2) * hid * 4 + hid * 4 * hid * 4
        if use_pallas() and lane_ok and vmem_bytes < 10 * 2 ** 20:
            hs, cs = fused_lstm(xg, w, h0, c0, lens)
        else:
            hs, cs = _lstm_seq_dense(xg, w, h0, c0, lens)
        # masking holds state past each row's length: the final step IS
        # the last valid h/c
        return {
            "Hidden": [hs],
            "CellSeq": [cs],
            "LastH": [hs[:, -1, :]],
            "LastC": [cs[:, -1, :]],
        }
    # reverse direction only from here (the forward path returned above):
    # scan the flipped sequence, flip the outputs back
    xs = jnp.flip(jnp.swapaxes(xproj, 0, 1), 0)  # [T, B, 4H]
    steps = jnp.flip(jnp.arange(t))

    def step(carry, inp):
        c_prev, h_prev = carry
        x_t, t_idx = inp
        gates = x_t + h_prev @ w
        if b is not None:
            gates = gates + b
        c, h = _lstm_cell(c_prev, h_prev, gates)
        if seq_len is not None:
            m = (t_idx < seq_len).astype(h.dtype)[:, None]
            c = m * c + (1 - m) * c_prev
            h = m * h + (1 - m) * h_prev
        return (c, h), (h, c)

    (c_fin, h_fin), (hs, cs) = jax.lax.scan(step, (c0, h0), (xs, steps))
    hs = jnp.flip(hs, 0)
    cs = jnp.flip(cs, 0)
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],
        "CellSeq": [jnp.swapaxes(cs, 0, 1)],
        "LastH": [h_fin],
        "LastC": [c_fin],
    }


@register("padded_gru")
def _padded_gru(ctx, ins, attrs):
    """GRU over padded [batch, time, 3*hidden] projected input (gru_op analog)."""
    xproj = ins["Input"][0]
    w = ins["Weight"][0]  # [H, 3H] -> [update|reset, candidate]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    bsz, t, h3 = xproj.shape
    hid = h3 // 3
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((bsz, hid), xproj.dtype)
    from .pallas_kernels import (
        _gru_seq_dense,
        _interpret,
        _row_block,
        fused_gru,
        use_pallas,
    )

    if not attrs.get("is_reverse", False):
        lens = (
            seq_len.reshape(-1).astype(jnp.int32)
            if seq_len is not None
            else jnp.full((bsz,), t, jnp.int32)
        )
        lane_ok = hid % (8 if _interpret() else 128) == 0
        # the whole [block_b, T, 4H] working set must fit in VMEM
        blk = _row_block(bsz, 8)
        vmem_bytes = blk * t * 4 * hid * 4 + hid * 3 * hid * 4
        if use_pallas() and lane_ok and vmem_bytes < 10 * 2 ** 20:
            hs = fused_gru(xproj, w, h0, lens)
        else:
            # one shared cell implementation (also the fused path's
            # backward recompute) — no formula triplication
            hs = _gru_seq_dense(xproj, w, h0, lens)
        # masking holds h past each row's length, so the final step IS the
        # last valid hidden state (lens==0 rows yield h0)
        return {"Hidden": [hs], "LastH": [hs[:, -1, :]]}
    w_rz = w[:, : 2 * hid]
    w_c = w[:, 2 * hid :]
    is_reverse = attrs.get("is_reverse", False)
    xs = jnp.swapaxes(xproj, 0, 1)
    if is_reverse:
        xs = jnp.flip(xs, 0)
    steps = jnp.arange(t)
    if is_reverse:
        steps = jnp.flip(steps)

    def step(h_prev, inp):
        x_t, t_idx = inp
        x_rz = x_t[:, : 2 * hid]
        x_c = x_t[:, 2 * hid :]
        # gate layout [update|reset|state], blend h = u*c + (1-u)*h_prev
        # (math/detail/gru_kernel.h:58-63: out = prev - u*prev + u*state)
        uz = jax.nn.sigmoid(x_rz + h_prev @ w_rz)
        u, r = jnp.split(uz, 2, axis=-1)
        c = jnp.tanh(x_c + (r * h_prev) @ w_c)
        h = u * c + (1 - u) * h_prev
        if seq_len is not None:
            m = (t_idx < seq_len).astype(h.dtype)[:, None]
            h = m * h + (1 - m) * h_prev
        return h, h

    h_fin, hs = jax.lax.scan(step, h0, (xs, steps))
    if is_reverse:
        hs = jnp.flip(hs, 0)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "LastH": [h_fin]}


# ---------------------------------------------------------------------------
# misc nn
# ---------------------------------------------------------------------------
@register("im2sequence")
def _im2sequence(ctx, ins, attrs):
    """Extract conv-style patches into a sequence (im2sequence_op.cc):
    x [N, C, H, W] -> [N, OH*OW, C*kh*kw] (padded layout; the reference
    emits LoD rows N*OH*OW x C*kh*kw)."""
    x = ins["X"][0]
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])  # up, left, down, right
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x,
        filter_shape=(kh, kw),
        window_strides=(sh, sw),
        padding=((pads[0], pads[2]), (pads[1], pads[3])),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, OH, OW]
    ckk = patches.shape[1]
    out = jnp.transpose(patches.reshape(n, ckk, -1), (0, 2, 1))
    return {"Out": [out]}


@register("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]
    oh, ow = attrs.get("out_h"), attrs.get("out_w")
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), method="bilinear")
    return {"Out": [out]}


@register("nearest_interp")
def _nearest_interp(ctx, ins, attrs):
    x = ins["X"][0]
    oh, ow = attrs.get("out_h"), attrs.get("out_w")
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), method="nearest")
    return {"Out": [out]}


@register("maxout")
def _maxout(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": [jnp.max(x.reshape(n, c // g, g, h, w), axis=2)]}


@register("pixel_shuffle")
def _pixel_shuffle(ctx, ins, attrs):
    x = ins["X"][0]
    r = attrs.get("upscale_factor", 2)
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return {"Out": [x.reshape(n, c // (r * r), h * r, w * r)]}


def _qvec_attention_mesh(q, k, v, qstart, scale, mesh, axis, bq_flag,
                         bk_flag, mosaic_legal):
    """The vector-QStart attention lowered MESH-CLEAN over `axis`
    (heads): under FLAGS_use_pallas the flash_attention_qvec kernel runs
    per-device inside shard_map (each shard sees its own [B, H/n, T, D]
    head slice and the full replicated qstart — per-row causal cutoffs
    are head-independent); otherwise a 4-D dense einsum bracketed by
    sharding constraints so the SPMD partitioner keeps the KV pool's
    heads-axis placement instead of re-laying it out.  q/k/v: rank-4
    [B, H, Tq|Tk, D]; qstart: [B]."""
    from ..flags import get_flag
    from ..parallel.mesh import shard_map
    from .pallas_kernels import NEG_INF, flash_attention_qvec, use_pallas
    from jax.sharding import NamedSharding, PartitionSpec as P

    b, h, t, d = q.shape
    tk = k.shape[2]
    p4 = P(None, axis, None, None)
    if use_pallas():
        bq = 128 if t % 128 == 0 else t
        bk = 128 if tk % 128 == 0 else tk
        if bq_flag or bk_flag:
            bq, bk = bq_flag or bq, bk_flag or bk
            if bq <= 0 or bk <= 0 or not mosaic_legal(bq, bk):
                raise ValueError(
                    "FLAGS_flash_block_q/k (%d, %d) are not Mosaic-legal "
                    "for the sharded ragged-step shapes Tq=%d, Tk=%d"
                    % (bq, bk, t, tk))
            dispatch = True
        else:
            # deterministic defaults under the mesh (the tuning-cache
            # search times STANDALONE kernels; a per-shard search inside
            # shard_map would attribute collective time to block sizes)
            dispatch = bq <= 512 and bk <= 1024
        if dispatch:
            def body(q4, k4, v4, qs):
                lb, lh, lt, ld = q4.shape
                ltk = k4.shape[2]
                qsv = jnp.repeat(qs.reshape(-1).astype(jnp.int32), lh)
                o = flash_attention_qvec(
                    q4.reshape(lb * lh, lt, ld),
                    k4.reshape(lb * lh, ltk, ld),
                    v4.reshape(lb * lh, ltk, ld),
                    qsv, scale, bq, bk)
                return o.reshape(lb, lh, lt, ld)

            return shard_map(
                body, mesh=mesh, in_specs=(p4, p4, p4, P()),
                out_specs=p4, check_rep=False)(q, k, v, qstart)
    sh = NamedSharding(mesh, p4)
    qc = jax.lax.with_sharding_constraint(q, sh)
    kc = jax.lax.with_sharding_constraint(k, sh)
    vc = jax.lax.with_sharding_constraint(v, sh)
    s = (jnp.einsum("bhqd,bhkd->bhqk", qc, kc).astype(jnp.float32)
         * scale)  # [B, H, Tq, Tk]
    q_pos = (qstart.reshape(b, 1).astype(jnp.int32)
             + jnp.arange(t, dtype=jnp.int32)[None, :])  # [B, Tq]
    keep = (q_pos[:, None, :, None]
            >= jnp.arange(tk, dtype=jnp.int32)[None, None, None, :])
    s = jnp.where(keep, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(qc.dtype), vc)
    return jax.lax.with_sharding_constraint(out, sh)


@register("fused_attention", no_grad_inputs=("QStart",))
def _fused_attention(ctx, ins, attrs):
    """Fused scaled-dot-product attention (the cuDNN-fused-kernel slot of
    the reference, TPU-style): flash kernel under FLAGS_use_pallas, dense
    XLA otherwise.  Q/K/V: [batch, heads, T, d]."""
    from .pallas_kernels import (
        _dense_attention,
        flash_attention,
        use_pallas,
    )

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    causal = bool(attrs.get("causal", False))
    window = int(attrs.get("window", 0) or 0)  # sliding-window (causal)
    if window < 0:
        raise ValueError("fused_attention: window must be >= 0")
    if window and not causal:
        raise ValueError(
            "fused_attention: window requires causal=True (consistent "
            "across the pallas and dense paths)")
    scale = attrs.get("scale") or 1.0 / (q.shape[-1] ** 0.5)
    b, h, t, d = q.shape
    tk = k.shape[2]
    # chunked-decode global query offset: query i at position QStart+i,
    # keys at their cache indices — Tq may differ from Tk.  A size-1
    # QStart is the classic scalar offset (one chunk position for the
    # whole batch); size B keeps PER-ROW offsets (ragged serving step).
    qstart = None
    if ins.get("QStart"):
        qstart = ins["QStart"][0].reshape(-1)
        qstart = qstart.reshape(()) if qstart.shape[0] == 1 else qstart
    if qstart is not None:
        if not causal:
            raise ValueError("fused_attention: QStart requires causal=True")
        if ins.get("Bias") or ins.get("SegmentIds"):
            raise ValueError(
                "fused_attention: QStart owns the causal cutoffs — "
                "Bias/SegmentIds are not combinable with it")
    elif causal and t != tk:
        raise ValueError(
            "fused_attention: causal requires Tq == Tk, got %d vs %d" % (t, tk)
        )
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    from ..flags import get_flag

    bq_flag = int(get_flag("flash_block_q") or 0)
    bk_flag = int(get_flag("flash_block_k") or 0)

    # Mosaic BlockSpec rule, per side: a block lands in the MINOR dim of
    # the lifted [BH, 1, X] lse/delta specs ((1, 1, block_q)) and the
    # kbias spec ((1, 1, block_k)), where it must be a multiple of 128
    # or cover the full dimension — and it must divide the dimension.
    # (Interpret mode does not enforce this; only a real-chip compile
    # does.)  The ONE statement of the rule: _mosaic_legal and
    # _legalize_blocks both consult these.
    def _legal_q(bq):
        return (bq % 128 == 0 or bq == t) and t % bq == 0

    def _legal_k(bk):
        return (bk % 128 == 0 or bk == tk) and tk % bk == 0

    def _mosaic_legal(bq, bk):
        return _legal_q(bq) and _legal_k(bk)

    def _legalize_blocks(bq, bk):
        """Re-legalize (possibly cached) block params against THIS
        call's seq lens: the tuning cache buckets row dims by pow2, so
        an entry seeded at a different Tq/Tk in the same bucket can
        carry blocks that do not divide these lengths — each illegal
        side falls back to its heuristic default instead of tripping
        the kernel's divisibility assert."""
        if not _legal_q(bq):
            bq = 128 if t % 128 == 0 else t
        if not _legal_k(bk):
            bk = 128 if tk % 128 == 0 else tk
        return bq, bk

    def _auto_blocks(kernel_tag, build):
        """Auto block choice through the persisted tuning cache: the
        legal (Mosaic + VMEM score-tile) candidate set is searched on a
        real-device miss; the heuristic 128-or-full default seeds
        interpret-mode entries.  build(params) -> standalone callable
        over (q, k, v) for the on-chip candidate timing."""
        from .pallas_kernels import _tuned

        default = {"block_q": 128 if t % 128 == 0 else t,
                   "block_k": 128 if tk % 128 == 0 else tk}
        cands = [
            {"block_q": cq, "block_k": ck}
            for cq in (128, 256, 512)
            for ck in (128, 256, 512, 1024)
            if _mosaic_legal(cq, ck)
        ]
        params = _tuned(
            kernel_tag, [(b * h, t, d), (b * h, tk, d)], q.dtype,
            cands, default, build=build,
            arg_specs=[((b * h, t, d), q.dtype),
                       ((b * h, tk, d), k.dtype),
                       ((b * h, tk, d), v.dtype)],
        )
        return _legalize_blocks(int(params["block_q"]),
                                int(params["block_k"]))

    if qstart is not None and qstart.ndim > 0:
        # PER-ROW offset-causal (the continuous-batching ragged step):
        # QStart is [B], row b's query i sits at global position
        # QStart[b] + i — every slot in the serving pool gets its own
        # causal cutoff inside ONE dispatch.  Under FLAGS_use_pallas
        # this rides the vector-qstart flash kernel (per-row SMEM
        # bases; row math is row-independent, so the serving
        # bit-exactness contract — a slot equals its solo run through
        # the same kernel — holds); dense XLA otherwise.
        if int(qstart.shape[0]) != b:
            raise ValueError(
                "fused_attention: vector QStart must be [batch]=%d, got %s"
                % (b, tuple(qstart.shape)))
        if window:
            raise ValueError(
                "fused_attention: window is not supported with per-row "
                "QStart")
        from .pallas_kernels import NEG_INF, flash_attention_qvec

        # GSPMD serving mesh (executor._run_spmd binds the context):
        # heads are embarrassingly parallel under per-row qstart, so the
        # mesh-clean form shards the HEADS axis — the pallas kernel
        # under shard_map (pallas_call has no SPMD partition rule; an
        # unwrapped call would force an all-gather of the sharded KV
        # pool), the dense form as a 4-D einsum with sharding
        # constraints (the flattened [B*H] layout would interleave
        # shards across batch rows).  Row math is untouched either way:
        # the serving exactness contract (pooled == solo through the
        # SAME program) rides through sharding.
        from ..parallel.partition_rules import current_spmd

        spmd = current_spmd()
        if spmd is not None:
            from ..parallel.mesh import mesh_axis_sizes

            mesh, rules = spmd
            axis = rules.mp_axis
            nsh = mesh_axis_sizes(mesh).get(axis, 1)
            if nsh > 1 and h % nsh == 0:
                return {"Out": [_qvec_attention_mesh(
                    q, k, v, qstart, float(scale), mesh, axis,
                    bq_flag, bk_flag, _mosaic_legal)]}
        if use_pallas():
            bq = 128 if t % 128 == 0 else t
            bk = 128 if tk % 128 == 0 else tk
            if bq_flag or bk_flag:
                # explicit sweep knobs: validate loudly and ALWAYS
                # dispatch — the auto path's VMEM-budget gate below
                # must not silently re-route a requested block size
                # onto the dense path (misattributed sweep timings)
                bq, bk = bq_flag or bq, bk_flag or bk
                if bq <= 0 or bk <= 0 or not _mosaic_legal(bq, bk):
                    raise ValueError(
                        "FLAGS_flash_block_q/k (%d, %d) are not "
                        "Mosaic-legal for the ragged-step shapes Tq=%d, "
                        "Tk=%d" % (bq, bk, t, tk))
                dispatch = True
            else:
                dispatch = bq <= 512 and bk <= 1024
                if dispatch:
                    # auto blocks ride the tuning cache like every other
                    # pallas_call site (searched at first on-chip
                    # dispatch)
                    bq, bk = _auto_blocks(
                        "flash_attention_qvec",
                        lambda p: (lambda q_, k_, v_:
                                   flash_attention_qvec(
                                       q_, k_, v_,
                                       jnp.zeros((q_.shape[0],),
                                                 jnp.int32),
                                       float(scale), p["block_q"],
                                       p["block_k"])))
            if dispatch:
                # each head row carries its batch row's base
                qsv = jnp.repeat(qstart.astype(jnp.int32), h)  # [B*H]
                out = flash_attention_qvec(qf, kf, vf, qsv, float(scale),
                                           bq, bk)
                return {"Out": [out.reshape(b, h, t, d)]}

        s = (jnp.einsum("bqd,bkd->bqk", qf, kf).astype(jnp.float32)
             * float(scale))  # [B*H, Tq, Tk]
        q_pos = (qstart.reshape(b, 1).astype(jnp.int32)
                 + jnp.arange(t, dtype=jnp.int32)[None, :])  # [B, Tq]
        keep = q_pos[:, :, None] >= jnp.arange(tk, dtype=jnp.int32)[
            None, None, :]  # [B, Tq, Tk]
        keep = jnp.broadcast_to(keep[:, None], (b, h, t, tk)).reshape(
            b * h, t, tk)
        s = jnp.where(keep, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bqk,bkd->bqd", p.astype(qf.dtype), vf)
        return {"Out": [out.reshape(b, h, t, d)]}
    kbias = None
    if ins.get("Bias"):
        # additive key-padding bias, rank-1 in the key axis: [B, Tk] (or any
        # shape squeezing to it, e.g. the reference-style [B, 1, 1, Tk]);
        # broadcast over heads and query rows without ever materializing
        # the [Tq, Tk] score matrix
        kbias = ins["Bias"][0].reshape(b, tk).astype(jnp.float32)
        kbias = jnp.broadcast_to(kbias[:, None, :], (b, h, tk)).reshape(b * h, tk)
    seg = None
    if ins.get("SegmentIds"):
        # sequence packing (reader.packing): [B, T] int ids; query i sees
        # key j iff the ids match.  Rides the flash kernels as two more
        # rank-1 [BH, T] operands (compared per score tile), dense
        # otherwise.
        if t != tk:
            raise ValueError(
                "fused_attention: SegmentIds requires Tq == Tk "
                "(self-attention over one packed row)")
        seg = ins["SegmentIds"][0].reshape(b, t).astype(jnp.int32)
        seg = jnp.broadcast_to(seg[:, None, :], (b, h, t)).reshape(b * h, t)
    if qstart is not None:
        from .pallas_kernels import flash_attention_piece

        if use_pallas() and (bq_flag or bk_flag):
            # sweep knobs apply here too: validate loudly and USE them —
            # silently benchmarking auto blocks (or the dense fallback)
            # under the requested label is the misattribution the
            # explicit-flag path exists to prevent
            bq, bk = bq_flag or 128, bk_flag or 128
            if bq <= 0 or bk <= 0 or not _mosaic_legal(bq, bk):
                raise ValueError(
                    "FLAGS_flash_block_q/k (%d, %d) are not Mosaic-legal "
                    "for the chunked-decode shapes Tq=%d, Tk=%d"
                    % (bq, bk, t, tk))
            out, _lse = flash_attention_piece(
                qf, kf, vf, True, float(scale), bq, bk, window,
                qstart.astype(jnp.int32))
            return {"Out": [out.reshape(b, h, t, d)]}
        bq = 128 if t % 128 == 0 else t
        bk = 128 if tk % 128 == 0 else tk
        if use_pallas() and bq <= 512 and bk <= 1024:
            bq, bk = _auto_blocks(
                "flash_attention_piece",
                lambda p: (lambda q_, k_, v_: flash_attention_piece(
                    q_, k_, v_, True, float(scale), p["block_q"],
                    p["block_k"], window,
                    jnp.zeros((1,), jnp.int32))[0]))
            # the ring's offset-causal piece IS chunked decode: the
            # piece is softmax-normalized within its kv, and here the
            # kv is the whole cache
            out, _lse = flash_attention_piece(
                qf, kf, vf, True, float(scale), bq, bk, window,
                qstart.astype(jnp.int32))
        else:
            out = _dense_attention(qf, kf, vf, True, float(scale),
                                   window=window, qoff=qstart)
        return {"Out": [out.reshape(b, h, t, d)]}
    if use_pallas() and (bq_flag or bk_flag):
        # explicit sweep knobs: validate loudly — a silently-ignored
        # flag would attribute fallback timings to the requested size
        bq = bq_flag or 128
        bk = bk_flag or 128
        if bq <= 0 or bk <= 0 or not _mosaic_legal(bq, bk):
            raise ValueError(
                "FLAGS_flash_block_q/k (%d, %d) are not Mosaic-legal for "
                "Tq=%d, Tk=%d: each block must divide its sequence length "
                "and be a multiple of 128 (or equal the full length) — "
                "the lse/delta/kbias BlockSpecs place the block in the "
                "minor dim" % (bq, bk, t, tk))
        out = flash_attention(qf, kf, vf, kbias, causal, float(scale),
                              block_q=bq, block_k=bk, window=window,
                              seg=seg)
    elif use_pallas():
        # auto path: 128-blocks when the lengths tile; otherwise a
        # single full-dim block is still Mosaic-legal, so short or odd
        # lengths ride flash too as long as the [bq, bk] score tile
        # stays VMEM-friendly.  Anything else goes dense.  The choice
        # among legal candidates goes through the tuning cache (searched
        # at first real-device dispatch, seeded in interpret mode).
        bq = 128 if t % 128 == 0 else t
        bk = 128 if tk % 128 == 0 else tk
        # this derivation is Mosaic-legal by construction (each block is
        # 128-tiling or full-dim); only the VMEM score-tile budget gates
        if bq <= 512 and bk <= 1024:
            bq, bk = _auto_blocks(
                "flash_attention",
                lambda p: (lambda q_, k_, v_: flash_attention(
                    q_, k_, v_, None, causal, float(scale),
                    p["block_q"], p["block_k"], window)))
            out = flash_attention(qf, kf, vf, kbias, causal, float(scale),
                                  block_q=bq, block_k=bk, window=window,
                                  seg=seg)
        else:
            out = _dense_attention(qf, kf, vf, causal, float(scale), kbias,
                                   window=window, seg=seg)
    else:
        out = _dense_attention(qf, kf, vf, causal, float(scale), kbias,
                               window=window, seg=seg)
    return {"Out": [out.reshape(b, h, t, d)]}


@register("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window convolution over padded sequences
    (sequence_ops/sequence_conv_op.cc): for each timestep concatenate
    context_length steps starting at context_start, matmul with Filter
    [ctx_len * D, out].  Positions outside the sequence contribute zeros
    (the reference's zero-padded context rows)."""
    x = ins["X"][0]  # [B, T, D]
    w = ins["Filter"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    ctx_len = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    ctx_start = int(attrs.get("contextStart", attrs.get("context_start", -1)))
    b, t, d = x.shape
    if seq_len is not None:
        mask = (jnp.arange(t)[None, :] < seq_len.reshape(-1, 1)).astype(x.dtype)
        x = x * mask[:, :, None]
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        shifted = jnp.roll(x, -off, axis=1)
        pos = jnp.arange(t) + off
        valid = ((pos >= 0) & (pos < t)).astype(x.dtype)
        cols.append(shifted * valid[None, :, None])
    ctx_mat = jnp.concatenate(cols, axis=-1)  # [B, T, ctx_len*D]
    return {"Out": [ctx_mat @ w]}


@register("attention_lstm")
def _attention_lstm(ctx, ins, attrs):
    """Fused attention LSTM (attention_lstm_op.cc): at every output step,
    score each source position with fc([x_t_src ; h_prev]), softmax over
    the (length-masked) sequence, take the context vector, run one LSTM
    cell on it.  Padded [B, T, M] re-expression of the LoD original."""
    x = ins["X"][0]  # [B, T, M]
    h0 = ins["H0"][0] if ins.get("H0") else None
    c0 = ins["C0"][0]
    att_w = ins["AttentionWeight"][0]  # [M + D, 1]
    att_b = ins["AttentionBias"][0] if ins.get("AttentionBias") else None
    lstm_w = ins["LSTMWeight"][0]  # [M + D, 4D]
    lstm_b = ins["LSTMBias"][0] if ins.get("LSTMBias") else None
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    b, t, m = x.shape
    dd = c0.shape[-1]
    if h0 is None:
        h0 = jnp.zeros_like(c0)

    neg = jnp.asarray(-1e9, x.dtype)
    if seq_len is not None:
        pad = jnp.arange(t)[None, :] >= seq_len.reshape(-1, 1)
    else:
        pad = jnp.zeros((b, t), bool)

    def step(carry, _):
        h, c = carry
        # attention scores over all T positions given h
        he = jnp.broadcast_to(h[:, None, :], (b, t, dd))
        feat = jnp.concatenate([x, he], axis=-1)  # [B, T, M+D]
        score = (feat @ att_w)[..., 0]
        if att_b is not None:
            score = score + att_b.reshape(-1)[0]
        score = jnp.where(pad, neg, score)
        alpha = jax.nn.softmax(score, axis=-1)
        ctx_vec = jnp.einsum("bt,btm->bm", alpha, x)
        gin = jnp.concatenate([ctx_vec, h], axis=-1) @ lstm_w
        if lstm_b is not None:
            gin = gin + lstm_b.reshape(1, -1)
        i, f, cc, o = jnp.split(gin, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(cc)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (h_fin, c_fin), hs = jax.lax.scan(step, (h0, c0), None, length=t)
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],  # [B, T, D]
        "Cell": [c_fin],
        "LastH": [h_fin],
    }


@register("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """conv3d_transpose_op: NCDHW transposed convolution via
    lax.conv_transpose (gradient-of-conv semantics on the MXU)."""
    x = ins["Input"][0]  # [N, C, D, H, W]
    w = ins["Filter"][0]  # [Cin, Cout, kD, kH, kW]
    strides = tuple(attrs.get("strides", [1, 1, 1]))
    pads = attrs.get("paddings", [0, 0, 0])
    dilations = list(attrs.get("dilations", [1, 1, 1]))
    groups = int(attrs.get("groups", 1) or 1)
    # paddle out = (D-1)*s - 2p + d*(k-1) + 1: jax pads the dilated input,
    # so each side takes d*(k-1) - p (see conv2d_transpose)
    jpads = [
        (dilations[i] * (w.shape[2 + i] - 1) - pads[i],) * 2 for i in range(3)
    ]

    def one(xg, wg):
        return jax.lax.conv_transpose(
            xg,
            wg,  # [Cin, Cout/g, kD, kH, kW]; Cin labeled 'O'
            strides,
            jpads,
            rhs_dilation=dilations,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
            transpose_kernel=True,
        )

    if groups == 1:
        out = one(x, w)
    else:
        cin = x.shape[1] // groups
        out = jnp.concatenate(
            [
                one(x[:, g * cin:(g + 1) * cin], w[g * cin:(g + 1) * cin])
                for g in range(groups)
            ],
            axis=1,
        )
    return {"Output": [out]}


@register("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    """pool_with_index_op 3-D variant: max pool + flat d*h*w argmax mask."""
    x = ins["X"][0]  # [N, C, D, H, W]
    ks = [int(k) for k in attrs.get("ksize", [2, 2, 2])]
    st = [int(s) for s in attrs.get("strides", ks)]
    n, c, d, h, w = x.shape
    kd, kh, kw = ks
    sd, sh, sw = st
    od, oh, ow = (d - kd) // sd + 1, (h - kh) // sh + 1, (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x.reshape(n * c, 1, d, h, w),
        (kd, kh, kw),
        (sd, sh, sw),
        "VALID",
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )  # [n*c, kd*kh*kw, od, oh, ow]
    patches = patches.reshape(n, c, kd * kh * kw, od, oh, ow)
    out = jnp.max(patches, axis=2)
    arg = jnp.argmax(patches, axis=2)
    wd = arg // (kh * kw)
    rem = arg % (kh * kw)
    wy, wx = rem // kw, rem % kw
    oz = jnp.arange(od).reshape(1, 1, -1, 1, 1)
    oy = jnp.arange(oh).reshape(1, 1, 1, -1, 1)
    ox = jnp.arange(ow).reshape(1, 1, 1, 1, -1)
    flat = ((oz * sd + wd) * h + (oy * sh + wy)) * w + (ox * sw + wx)
    return {"Out": [out], "Mask": [flat.astype(jnp.int32)]}


@register("data_norm")
def _data_norm(ctx, ins, attrs):
    """data_norm_op.cc: normalization by accumulated batch statistics
    (CTR models): means = BatchSum/BatchSize, scales =
    sqrt(BatchSize / BatchSquareSum); training also emits updated
    accumulators for the current minibatch."""
    x = ins["X"][0]  # [B, D]
    bsz = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsq = ins["BatchSquareSum"][0]
    eps = float(attrs.get("epsilon", 1e-4))
    means = bsum / jnp.maximum(bsz, 1.0)
    scales = jnp.sqrt(jnp.maximum(bsz, 1.0) / jnp.maximum(bsq, eps))
    out = (x - means.reshape(1, -1)) * scales.reshape(1, -1)
    nb = x.shape[0]
    upd_size = bsz + nb
    upd_sum = bsum + jnp.sum(x, axis=0)
    upd_sq = bsq + jnp.sum(x * x, axis=0)
    return {
        "Y": [out],
        "Means": [means],
        "Scales": [scales],
        "BatchSizeOut": [upd_size],
        "BatchSumOut": [upd_sum],
        "BatchSquareSumOut": [upd_sq],
    }


@register("seq_cache_write", no_grad_inputs=("Pos",))
def _seq_cache_write(ctx, ins, attrs):
    """KV-cache update for incremental decode: write the current chunk's
    [B, H, W, D] projections into the [B, H, T, D] cache at time indices
    Pos..Pos+W-1 (W == 1 is the classic one-token step; W > 1 is the
    chunked-prefill write).  Static shapes — one dynamic_update_slice on
    the time axis.  NB dynamic_update_slice CLAMPS Pos to T-W; callers
    validate lengths up front (decode_cache.validate_cached_call)."""
    cache, new, pos = ins["Cache"][0], ins["New"][0], ins["Pos"][0]
    pos = pos.reshape(()).astype(jnp.int32)
    zero = jnp.int32(0)
    return {"Out": [jax.lax.dynamic_update_slice(
        cache, new.astype(cache.dtype), (zero, zero, pos, zero))]}


@register("slot_cache_write", no_grad_inputs=("Pos", "Width"))
def _slot_cache_write(ctx, ins, attrs):
    """PER-ROW ragged KV-cache update (the continuous-batching serving
    step): write New [B, H, W, D] into Cache [B, H, T, D] where row b's
    column i lands at time index Pos[b] + i, but ONLY for i < Width[b]
    — a decoding slot writes one token (Width 1), a prefilling slot a
    whole chunk (Width <= W), a free slot nothing (Width 0).  Invalid
    columns (beyond Width, or past the cache) are DROPPED, never
    clamped: a clamp would silently overwrite a neighbor request's live
    keys, which is exactly the cross-request interference the serving
    exactness contract forbids."""
    cache, new = ins["Cache"][0], ins["New"][0]
    pos = ins["Pos"][0].reshape(-1).astype(jnp.int32)
    width = ins["Width"][0].reshape(-1).astype(jnp.int32)
    t_max = cache.shape[2]
    w = new.shape[2]
    col = jnp.arange(w, dtype=jnp.int32)
    idx = pos[:, None] + col[None, :]  # [B, W]
    valid = (col[None, :] < width[:, None]) & (idx < t_max)
    # out-of-bounds index == dropped under mode="drop": route every
    # invalid column to t_max
    idx = jnp.where(valid, idx, t_max)

    # GSPMD serving mesh: the write indexes the TIME axis only, so a
    # heads-axis-sharded pool updates shard-locally; the constraints pin
    # that placement (without them the partitioner may round-trip the
    # whole pool through a replicated scatter)
    sh = None
    from ..parallel.partition_rules import current_spmd

    spmd = current_spmd()
    if spmd is not None:
        from ..parallel.mesh import mesh_axis_sizes

        mesh, rules = spmd
        nsh = mesh_axis_sizes(mesh).get(rules.mp_axis, 1)
        if nsh > 1 and cache.shape[1] % nsh == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(mesh,
                               P(None, rules.mp_axis, None, None))
            cache = jax.lax.with_sharding_constraint(cache, sh)
            new = jax.lax.with_sharding_constraint(new, sh)

    def row(c, n, i):
        # c [H, T, D], n [H, W, D], i [W]
        return c.at[:, i, :].set(n, mode="drop")

    out = jax.vmap(row)(cache, new.astype(cache.dtype), idx)
    if sh is not None:
        out = jax.lax.with_sharding_constraint(out, sh)
    return {"Out": [out]}


@register("decode_pos_mask", no_grad_inputs=("Pos",))
def _decode_pos_mask(ctx, ins, attrs):
    """[B, T] additive key bias for cached decode: 0 for key positions
    <= Pos, -1e30 beyond — the dynamic-length mask fused_attention's
    rank-1 Bias slot consumes."""
    pos = ins["Pos"][0].reshape(()).astype(jnp.int32)
    t = int(attrs["t_max"])
    b = int(attrs["batch"])
    row = jnp.where(jnp.arange(t, dtype=jnp.int32) <= pos, 0.0, -1e30)
    return {"Out": [jnp.broadcast_to(row[None, :], (b, t)).astype(jnp.float32)]}


@register("rotary_embed", no_grad_inputs=("Pos",))
def _rotary_embed(ctx, ins, attrs):
    """Rotary position embedding (RoPE, rotate-half convention) applied
    to per-head projections [B, H, T, Dh].  Pos: optional int positions
    [T] (defaults to arange(T)); the cached decode path feeds the single
    current position so cache-resident keys are stored pre-rotated.
    Beyond-reference (the reference era used learned/sinusoid absolute
    positions); standard in modern decoder LMs."""
    x = ins["X"][0]
    base = float(attrs.get("base", 10000.0))
    t = x.shape[2]
    if x.shape[-1] % 2:
        raise ValueError(
            "rotary_embed: head dim must be even (rotate-half pairs), "
            "got %d" % x.shape[-1])
    half = x.shape[-1] // 2
    freq = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    if ins.get("Pos") and ins["Pos"][0].ndim == 2:
        # PER-ROW positions [B, T] (ragged serving step: each pool slot
        # rotates by its own request's positions)
        pos = ins["Pos"][0].astype(jnp.float32)
        ang = pos[:, :, None] * freq[None, None, :]  # [B, T, half]
        sin = jnp.sin(ang)[:, None].astype(x.dtype)  # [B, 1, T, half]
        cos = jnp.cos(ang)[:, None].astype(x.dtype)
        x1, x2 = x[..., :half], x[..., half:]
        return {"Out": [jnp.concatenate(
            [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)]}
    if ins.get("Pos"):
        pos = ins["Pos"][0].reshape(-1).astype(jnp.float32)
    else:
        pos = jnp.arange(t, dtype=jnp.float32)
    ang = pos[:, None] * freq[None, :]  # [T, half]
    sin = jnp.sin(ang)[None, None].astype(x.dtype)
    cos = jnp.cos(ang)[None, None].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py)
# ---------------------------------------------------------------------------
from ..analysis.infer import (  # noqa: E402
    InferError,
    VarInfo,
    numel_known,
    register_infer,
    same_as,
    same_dtype,
    slot_info as _vi,
)


def _conv_hw(dim, k, s, p, d, ceil_mode=False):
    if dim < 0:
        return -1
    eff = d * (k - 1) + 1
    num = dim + 2 * p - eff
    if num < 0:
        raise InferError(
            "conv/pool window (k=%d, dilation=%d) exceeds padded input "
            "dim %d" % (k, d, dim + 2 * p))
    if ceil_mode:
        return -(-num // s) + 1
    return num // s + 1


@register_infer("conv2d", req_ins=("Input", "Filter"), req_outs=("Output",))
@register_infer("depthwise_conv2d", req_ins=("Input", "Filter"),
                req_outs=("Output",))
def _conv2d_infer(op, ins):
    x, w = _vi(ins, "Input"), _vi(ins, "Filter")
    if x is None or x.shape is None or w is None or w.shape is None:
        return {}
    if len(x.shape) != 4 or len(w.shape) != 4:
        raise InferError(
            "conv2d expects rank-4 Input/Filter, got %s / %s"
            % (x.shape, w.shape))
    a = op.attrs
    strides = _pair(a.get("strides", [1, 1]))
    pads = _pair(a.get("paddings", [0, 0]))
    dils = _pair(a.get("dilations", [1, 1]))
    nhwc = a.get("data_format", "NCHW") == "NHWC"
    h_ax, w_ax, c_ax = (1, 2, 3) if nhwc else (2, 3, 1)
    groups = a.get("groups", 1) or 1
    if op.type == "depthwise_conv2d":
        groups = x.shape[c_ax] if x.shape[c_ax] >= 0 else groups
    cin = x.shape[c_ax]
    if cin >= 0 and w.shape[1] >= 0 and groups and cin != w.shape[1] * groups:
        raise InferError(
            "conv2d channel mismatch: input C=%d vs Filter[1]*groups=%d*%d"
            % (cin, w.shape[1], groups))
    oh = _conv_hw(x.shape[h_ax], w.shape[2], strides[0], pads[0], dils[0])
    ow = _conv_hw(x.shape[w_ax], w.shape[3], strides[1], pads[1], dils[1])
    shape = [x.shape[0], 0, 0, 0]
    shape[h_ax], shape[w_ax], shape[c_ax] = oh, ow, w.shape[0]
    return {"Output": [VarInfo(tuple(shape), x.dtype)]}


@register_infer("pool2d", req_ins=("X",))
def _pool2d_infer(op, ins):
    x = _vi(ins, "X")
    if x is None or x.shape is None:
        return {}
    if len(x.shape) != 4:
        raise InferError("pool2d expects rank-4 input, got %s" % (x.shape,))
    a = op.attrs
    nhwc = a.get("data_format", "NCHW") == "NHWC"
    sp = (1, 2) if nhwc else (2, 3)
    shape = list(x.shape)
    if a.get("global_pooling", False) or (
            a.get("adaptive", False) and list(a.get("ksize")) == [1, 1]):
        shape[sp[0]] = shape[sp[1]] = 1
        return {"Out": [VarInfo(tuple(shape), x.dtype)]}
    ksize = _pair(a.get("ksize", [2, 2]))
    strides = _pair(a.get("strides", [1, 1]))
    pads = _pair(a.get("paddings", [0, 0]))
    ceil = bool(a.get("ceil_mode", False))
    shape[sp[0]] = _conv_hw(x.shape[sp[0]], ksize[0], strides[0], pads[0],
                            1, ceil)
    shape[sp[1]] = _conv_hw(x.shape[sp[1]], ksize[1], strides[1], pads[1],
                            1, ceil)
    return {"Out": [VarInfo(tuple(shape), x.dtype)]}


@register_infer("batch_norm", req_ins=("X", "Scale", "Bias", "Mean",
                                       "Variance"), req_outs=("Y",))
def _bn_infer(op, ins):
    x, mean = _vi(ins, "X"), _vi(ins, "Mean")
    xi = VarInfo(x.shape, x.dtype) if x is not None else None
    stat = VarInfo(mean.shape, None) if mean is not None else None
    return {
        "Y": [xi],
        "MeanOut": [stat], "VarianceOut": [stat],
        "SavedMean": [stat], "SavedVariance": [stat],
    }


@register_infer("layer_norm", req_ins=("X",), req_outs=("Y",))
def _ln_infer(op, ins):
    x = _vi(ins, "X")
    if x is None:
        return {}
    begin = int(op.attrs.get("begin_norm_axis", 1))
    stat = None
    if x.shape is not None:
        stat = VarInfo(x.shape[:begin], None)
    return {"Y": [VarInfo(x.shape, x.dtype)],
            "Mean": [stat], "Variance": [stat]}


@register_infer("dropout", req_ins=("X",))
def _dropout_infer(op, ins):
    x = _vi(ins, "X")
    xi = VarInfo(x.shape, x.dtype) if x is not None else None
    return {"Out": [xi], "Mask": [xi]}


@register_infer("fc", req_ins=("Input", "W"))
def _fc_infer(op, ins):
    x, w = _vi(ins, "Input"), _vi(ins, "W")
    if x is None or x.shape is None or w is None or w.shape is None:
        return {"Out": [VarInfo(None, same_dtype(x, w))]}
    k = int(op.attrs.get("in_num_col_dims", 1))
    xk = numel_known(x.shape[k:])
    if (len(w.shape) == 2 and xk is not None and w.shape[0] >= 0
            and xk != w.shape[0]):
        raise InferError(
            "fc contraction mismatch: Input%s flattens to K=%d but W%s "
            "expects K=%d" % (x.shape, xk, w.shape, w.shape[0]))
    return {"Out": [VarInfo(tuple(x.shape[:k]) + (w.shape[-1],),
                            same_dtype(x, w))]}


@register_infer("fused_swiglu", req_ins=("X", "GateW", "UpW"))
def _swiglu_infer(op, ins):
    x, wg = _vi(ins, "X"), _vi(ins, "GateW")
    if x is None or x.shape is None or wg is None or wg.shape is None:
        return {}
    k = int(op.attrs.get("x_num_col_dims", 1))
    return {"Out": [VarInfo(tuple(x.shape[:k]) + (wg.shape[-1],),
                            same_dtype(x, wg))]}


@register_infer("fused_residual_ln", req_ins=("X", "Y", "Scale", "Bias"),
                req_outs=("Y", "Sum"))
def _frln_infer(op, ins):
    x = _vi(ins, "X")
    if x is None:
        return {}
    xi = VarInfo(x.shape, x.dtype)
    stat = VarInfo(x.shape[:-1], None) if x.shape is not None else None
    return {"Sum": [xi], "Y": [xi], "Mean": [stat], "Variance": [stat]}


@register_infer("fused_attention", req_ins=("Q", "K", "V"))
def _fattn_infer(op, ins):
    q, k, v = _vi(ins, "Q"), _vi(ins, "K"), _vi(ins, "V")
    for name, t in (("Q", q), ("K", k), ("V", v)):
        if t is not None and t.shape is not None and len(t.shape) != 4:
            raise InferError(
                "fused_attention %s must be rank-4 [B, H, T, D], got %s"
                % (name, t.shape))
    if (q is not None and k is not None and q.shape is not None
            and k.shape is not None and q.shape[-1] >= 0
            and k.shape[-1] >= 0 and q.shape[-1] != k.shape[-1]):
        raise InferError(
            "fused_attention head-dim mismatch: Q%s vs K%s"
            % (q.shape, k.shape))
    return {"Out": [VarInfo(q.shape if q else None, q.dtype if q else None)]}


register_infer("seq_cache_write", req_ins=("Cache", "New", "Pos"))(
    same_as("Cache"))
register_infer("slot_cache_write",
               req_ins=("Cache", "New", "Pos", "Width"))(same_as("Cache"))
register_infer("rotary_embed", req_ins=("X",))(same_as("X"))


@register_infer("decode_pos_mask", req_ins=("Pos",))
def _dpm_infer(op, ins):
    return {"Out": [VarInfo(
        (int(op.attrs["batch"]), int(op.attrs["t_max"])), "float32")]}
