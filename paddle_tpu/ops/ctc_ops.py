"""CTC ops (operators/warpctc_op.cc, ctc_align_op.cc, edit_distance_op.cc).

The reference dlopens warp-ctc (platform/dynload/warpctc.h); on TPU the CTC
loss is a log-domain alpha recursion compiled by XLA (via optax.ctc_loss),
batched over the whole padded batch — no external library.
"""

import jax
import jax.numpy as jnp
import optax

from ..core.registry import register


@register("warpctc", no_grad_inputs=("Label", "LogitsLength", "LabelLength"))
def _warpctc(ctx, ins, attrs):
    """CTC loss. Padded layout: Logits [B, T, C] (unnormalized), Label
    [B, L] int32 (0..C-2; blank index per attr), LogitsLength [B],
    LabelLength [B]. Output Loss [B, 1]."""
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    b, t, c = logits.shape
    blank = attrs.get("blank", 0)
    if ins.get("LogitsLength"):
        llen = ins["LogitsLength"][0].reshape(-1).astype(jnp.int32)
    else:
        llen = jnp.full((b,), t, jnp.int32)
    if ins.get("LabelLength"):
        lablen = ins["LabelLength"][0].reshape(-1).astype(jnp.int32)
    else:
        lablen = jnp.full((b,), label.shape[1], jnp.int32)
    tpos = jnp.arange(t)[None, :]
    logit_pad = (tpos >= llen[:, None]).astype(jnp.float32)
    lpos = jnp.arange(label.shape[1])[None, :]
    label_pad = (lpos >= lablen[:, None]).astype(jnp.float32)
    # optax expects blank==0; rotate classes if needed. Labels arrive
    # compressed over the C-1 non-blank classes (0..C-2): compressed l is
    # full class l (l < blank) or l+1 (l >= blank), and after rotating the
    # logits so blank sits at 0, both cases land on index l+1.
    if blank != 0:
        perm = jnp.concatenate(
            [jnp.asarray([blank]), jnp.delete(jnp.arange(c), blank, assume_unique_indices=True)]
        )
        logits = logits[:, :, perm]
    label = label + 1
    loss = optax.ctc_loss(logits, logit_pad, label.astype(jnp.int32), label_pad)
    norm = attrs.get("norm_by_times", False)
    if norm:
        loss = loss / jnp.maximum(llen.astype(loss.dtype), 1.0)
    return {"Loss": [loss.reshape(-1, 1)], "WarpCTCGrad": [jnp.zeros_like(logits)]}


@register("ctc_align", no_grad_inputs=("Input", "InputLength"))
def _ctc_align(ctx, ins, attrs):
    """Remove repeats then blanks (ctc_align_op.cc). Padded [B, T] int;
    output padded [B, T] with -1 (or pad_value) past the decoded length,
    plus OutputLength [B]."""
    x = ins["Input"][0].astype(jnp.int32)
    blank = attrs.get("blank", 0)
    pad_value = attrs.get("padding_value", 0)
    b, t = x.shape
    if ins.get("InputLength"):
        ilen = ins["InputLength"][0].reshape(-1).astype(jnp.int32)
    else:
        ilen = jnp.full((b,), t, jnp.int32)
    pos = jnp.arange(t)[None, :]
    valid = pos < ilen[:, None]
    prev = jnp.concatenate([jnp.full((b, 1), -1, x.dtype), x[:, :-1]], axis=1)
    keep = valid & (x != blank) & (x != prev)
    # stable compaction: dest index = cumsum(keep) - 1
    dest = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_len = jnp.maximum(dest[:, -1] + 1, 0)
    out = jnp.full((b, t), pad_value, x.dtype)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    dest_safe = jnp.where(keep, dest, t - 1)
    # scatter kept values; use add-safe set with masked dummy column trick
    out = out.at[rows, dest_safe].set(jnp.where(keep, x, out[rows, dest_safe]))
    return {"Output": [out], "OutputLength": [out_len.reshape(-1, 1)]}


@register("edit_distance", no_grad_inputs=("Hyps", "Refs", "HypsLength", "RefsLength"))
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per pair (edit_distance_op.cc). Padded
    Hyps [B, M], Refs [B, N] + lengths; DP over the reference axis via
    lax.scan, vectorized over batch and hyp axis."""
    hyp = ins["Hyps"][0].astype(jnp.int32)
    ref = ins["Refs"][0].astype(jnp.int32)
    b, m = hyp.shape
    n = ref.shape[1]
    if ins.get("HypsLength"):
        hlen = ins["HypsLength"][0].reshape(-1).astype(jnp.int32)
    else:
        hlen = jnp.full((b,), m, jnp.int32)
    if ins.get("RefsLength"):
        rlen = ins["RefsLength"][0].reshape(-1).astype(jnp.int32)
    else:
        rlen = jnp.full((b,), n, jnp.int32)

    # row DP: dist[j] over hyp prefix length j (0..m)
    row0 = jnp.broadcast_to(jnp.arange(m + 1, dtype=jnp.float32), (b, m + 1))

    def step(row, i):
        # process ref token i (0-based); new row over hyp prefixes
        r_i = jnp.take_along_axis(ref, jnp.minimum(i, n - 1)[None, None].repeat(b, 0), axis=1)[:, 0]
        sub_cost = (hyp != r_i[:, None]).astype(jnp.float32)  # [B, M]
        # new[0] = i+1
        def inner(carry, j):
            # carry = new[j]; compute new[j+1]
            prev_new = carry
            dele = row[:, j + 1] + 1.0
            ins_ = prev_new + 1.0
            sub = row[:, j] + sub_cost[:, j]
            val = jnp.minimum(jnp.minimum(dele, ins_), sub)
            return val, val

        first = jnp.full((b,), (i + 1).astype(jnp.float32))
        _, rest = jax.lax.scan(inner, first, jnp.arange(m))
        new_row = jnp.concatenate([first[:, None], jnp.swapaxes(rest, 0, 1)], axis=1)
        active = (i < rlen)[:, None]
        return jnp.where(active, new_row, row), None

    row, _ = jax.lax.scan(step, row0, jnp.arange(n))
    dist = jnp.take_along_axis(row, hlen[:, None], axis=1)[:, 0]
    # empty-ref convention: distance = hyp length
    dist = jnp.where(rlen == 0, hlen.astype(dist.dtype), dist)
    if attrs.get("normalized", True):
        dist = dist / jnp.maximum(rlen.astype(dist.dtype), 1.0)
    return {
        "Out": [dist.reshape(-1, 1)],
        "SequenceNum": [jnp.asarray(b, jnp.int32)],
    }
