"""Fake-quantization ops for QAT (operators/fake_quantize_op.cc,
fake_dequantize_op.cc) — quantize-dequantize roundtrips with a
straight-through estimator so XLA keeps the graph differentiable.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


def _ste_round(x):
    # straight-through: round in fwd, identity grad
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def _quant_dequant(x, scale, bits):
    rng = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(_ste_round(x / s * rng), -rng, rng)
    return q * s / rng


@register("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    return {
        "Out": [_quant_dequant(x, scale, bits)],
        "OutScale": [jax.lax.stop_gradient(scale.reshape(1))],
    }


@register("fake_quantize_range_abs_max", no_grad_inputs=("InScale", "InScales", "Iter"))
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """Sliding-window abs-max (fake_quantize_op.cc FindRangeAbsMaxFunctor):
    InScales is a window_size ring buffer of recent batch maxima; the scale
    is the max over the window, so an early outlier ages out."""
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = attrs.get("bit_length", 8)
    window = attrs.get("window_size", 10000)
    is_test = attrs.get("is_test", False) or ctx.is_test
    cur = jnp.max(jnp.abs(x))
    if ins.get("InScales"):
        buf = ins["InScales"][0].reshape(-1)
        it = ins["Iter"][0].reshape(()).astype(jnp.int32) if ins.get("Iter") else jnp.int32(0)
        new_buf = jnp.where(is_test, buf, buf.at[it % buf.shape[0]].set(cur))
        scale = jnp.where(is_test, in_scale, jnp.max(new_buf))
        return {
            "Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [jax.lax.stop_gradient(scale.reshape(1))],
            "OutScales": [jax.lax.stop_gradient(new_buf)],
        }
    scale = jnp.where(is_test, in_scale, jnp.maximum(cur, in_scale))
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [jax.lax.stop_gradient(scale.reshape(1))]}


@register("fake_quantize_moving_average_abs_max", no_grad_inputs=("InScale", "InAccum", "InState"))
def _fake_quantize_moving_average_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    cur = jnp.max(jnp.abs(x))
    state = ins["InState"][0].reshape(()) if ins.get("InState") else jnp.asarray(1.0)
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else in_scale
    new_state = jnp.where(is_test, state, rate * state + 1.0)
    new_accum = jnp.where(is_test, accum, rate * accum + cur)
    scale = jnp.where(is_test, in_scale, new_accum / new_state)
    return {
        "Out": [_quant_dequant(x, scale, bits)],
        "OutScale": [jax.lax.stop_gradient(scale.reshape(1))],
        "OutState": [jax.lax.stop_gradient(new_state.reshape(1))],
        "OutAccum": [jax.lax.stop_gradient(new_accum.reshape(1))],
    }


@register("fake_channel_wise_quantize_abs_max")
def _fake_channel_wise_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    axes = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=axes)
    shp = [-1] + [1] * (x.ndim - 1)
    out = _quant_dequant(x, scale.reshape(shp), bits)
    return {"Out": [out], "OutScale": [jax.lax.stop_gradient(scale)]}


@register("fake_dequantize_max_abs", no_grad_inputs=("Scale",))
def _fake_dequantize_max_abs(ctx, ins, attrs):
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = attrs.get("max_range", 127.0)
    return {"Out": [x * scale.reshape(()) / max_range]}


# ---------------------------------------------------------------------------
# Real-int8 inference ops (the TensorRT-int8 capability, TPU-native:
# inference/tensorrt/convert/*.cc precedent).  Produced by
# QuantizeTranspiler.convert_to_int8 from a frozen QAT program: the
# weight arrives pre-quantized int8 with its scale, the activation is
# quantized in-op (stored scale when the QAT type kept one, dynamic
# abs-max otherwise), and the integer accumulation runs at int32 before
# one fused dequant rescale.
# ---------------------------------------------------------------------------
def _act_to_int8(x, ins, rng):
    """Quantize the f32 activation: InScale (frozen range/moving scale)
    when present, else dynamic abs-max.  Returns (int8 x, f32 scale)."""
    if ins.get("InScale"):
        s = ins["InScale"][0].reshape(())
    else:
        s = jnp.max(jnp.abs(x))
    s = jnp.maximum(s.astype(jnp.float32), 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s * rng), -rng, rng)
    return q.astype(jnp.int8), s


def _dequant_w(ins, attrs, rng, ch_axis=0):
    """Weight-only mode: reconstruct the float weight from int8 + scale
    (XLA fuses this dequant into the consuming matmul/conv read)."""
    w = ins["Y" if "Y" in ins else "Filter"][0]
    sw = ins["WScale"][0]
    if int(sw.size) > 1:  # per-out-channel (conv)
        bshape = [1] * w.ndim
        bshape[ch_axis] = int(sw.size)
        return w.astype(jnp.float32) * (sw.reshape(bshape) / rng)
    return w.astype(jnp.float32) * (sw.reshape(()) / rng)


@register("quantized_mul")
def _quantized_mul(ctx, ins, attrs):
    x, w = ins["X"][0], ins["Y"][0]  # w: int8 [K, N]
    rng = float(2 ** (attrs.get("bit_length", 8) - 1) - 1)
    xn = attrs.get("x_num_col_dims", 1)
    if attrs.get("weight_only"):
        wf = _dequant_w(ins, attrs, rng).astype(x.dtype)
        lead = 1
        for d in x.shape[:xn]:
            lead *= d
        out = x.reshape(lead, -1) @ wf
        return {"Out": [out.reshape(tuple(x.shape[:xn]) + tuple(w.shape[1:]))]}
    lead = 1
    for d in x.shape[:xn]:
        lead *= d
    x2 = x.reshape(lead, -1)
    xq, sx = _act_to_int8(x2, ins, rng)
    acc = jax.lax.dot_general(
        xq, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    sw = ins["WScale"][0].reshape(())  # scalar weight scale (abs_max)
    out = acc.astype(jnp.float32) * (sx / rng) * (sw / rng)
    out_shape = tuple(x.shape[:xn]) + tuple(w.shape[1:])
    return {"Out": [out.reshape(out_shape)]}


@register("quantized_matmul")
def _quantized_matmul(ctx, ins, attrs):
    x, w = ins["X"][0], ins["Y"][0]
    rng = float(2 ** (attrs.get("bit_length", 8) - 1) - 1)
    if attrs.get("weight_only"):
        from .math_ops import _matmul

        wf = _dequant_w(ins, attrs, rng).astype(x.dtype)
        return _matmul(ctx, {"X": [x], "Y": [wf]}, attrs)
    if attrs.get("transpose_Y", False):
        w = jnp.swapaxes(w, -1, -2)
    xq, sx = _act_to_int8(x, ins, rng)
    if attrs.get("transpose_X", False):
        xq = jnp.swapaxes(xq, -1, -2)
    acc = jax.lax.dot_general(
        xq, w,
        (((xq.ndim - 1,), (w.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    sw = ins["WScale"][0].reshape(())
    alpha = attrs.get("alpha", 1.0)
    out = acc.astype(jnp.float32) * (alpha * sx / rng) * (sw / rng)
    return {"Out": [out]}


def _quantized_conv_impl(ctx, ins, attrs, groups=None):
    from .nn_ops import _pair

    x, w = ins["Input"][0], ins["Filter"][0]  # w: int8 OIHW
    rng = float(2 ** (attrs.get("bit_length", 8) - 1) - 1)
    if attrs.get("weight_only"):
        from .nn_ops import _conv2d, _depthwise_conv2d

        wf = _dequant_w(ins, attrs, rng, ch_axis=0).astype(x.dtype)
        sub = dict(ins)
        sub["Filter"] = [wf]
        fn = _depthwise_conv2d if groups == "depthwise" else _conv2d
        return fn(ctx, sub, attrs)
    fmt = attrs.get("data_format", "NCHW")
    ch_axis = 1 if fmt == "NCHW" else x.ndim - 1
    if groups == "depthwise":
        groups = x.shape[ch_axis]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    xq, sx = _act_to_int8(x, ins, rng)
    acc = jax.lax.conv_general_dilated(
        xq,
        w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=(fmt, "OIHW", fmt),
        feature_group_count=groups or attrs.get("groups", 1) or 1,
        preferred_element_type=jnp.int32,
    )
    # weight scale: [1] (abs_max) or [Co] (channel-wise), broadcast on
    # the out-channel axis
    sw = ins["WScale"][0]
    bshape = [1] * acc.ndim
    if int(sw.size) > 1:
        bshape[ch_axis] = int(sw.size)
    out = acc.astype(jnp.float32) * (sx / rng) * (sw.reshape(bshape) / rng)
    # conv epilogue parity with _conv2d (nn_ops.py): a fused bias add
    # and/or relu (conv_eltadd_relu_fuse_pass output) must survive the
    # int8 rewrite
    if ins.get("Bias"):
        bb = [1] * acc.ndim
        bb[ch_axis] = -1
        out = out + ins["Bias"][0].reshape(bb)
    if attrs.get("fuse_relu"):
        out = jnp.maximum(out, 0)
    return {"Output": [out]}


@register("quantized_conv2d")
def _quantized_conv2d(ctx, ins, attrs):
    return _quantized_conv_impl(ctx, ins, attrs)


@register("quantized_depthwise_conv2d")
def _quantized_depthwise_conv2d(ctx, ins, attrs):
    return _quantized_conv_impl(ctx, ins, attrs, groups="depthwise")


@register("quantized_lookup_table", no_grad_inputs=("Ids", "W", "WScale"))
def _quantized_lookup_table(ctx, ins, attrs):
    """Weight-only int8 embedding lookup: gather int8 rows, dequant by
    the scale — per-row (WScale shape [V], gathered alongside the rows
    so no extra HBM traffic beyond 4 bytes/id) or per-tensor
    (scalar).  The gather reads ~1/4 the HBM of f32 rows."""
    w, ids = ins["W"][0], ins["Ids"][0]
    rng = float(2 ** (attrs.get("bit_length", 8) - 1) - 1)
    sw = ins["WScale"][0]
    ids = ids.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    rows = jnp.take(w, ids, axis=0).astype(jnp.float32)
    if sw.ndim >= 1 and sw.size > 1:  # per-row scales
        out = rows * (jnp.take(sw, ids, axis=0)[..., None] / rng)
    else:
        out = rows * (sw.reshape(()) / rng)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        mask = (ids != pad).astype(out.dtype)[..., None]
        out = out * mask
    return {"Out": [out]}
