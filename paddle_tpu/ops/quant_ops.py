"""Fake-quantization ops for QAT (operators/fake_quantize_op.cc,
fake_dequantize_op.cc) — quantize-dequantize roundtrips with a
straight-through estimator so XLA keeps the graph differentiable.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


def _ste_round(x):
    # straight-through: round in fwd, identity grad
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def _quant_dequant(x, scale, bits):
    rng = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(_ste_round(x / s * rng), -rng, rng)
    return q * s / rng


@register("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    return {
        "Out": [_quant_dequant(x, scale, bits)],
        "OutScale": [jax.lax.stop_gradient(scale.reshape(1))],
    }


@register("fake_quantize_range_abs_max", no_grad_inputs=("InScale", "InScales", "Iter"))
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """Sliding-window abs-max (fake_quantize_op.cc FindRangeAbsMaxFunctor):
    InScales is a window_size ring buffer of recent batch maxima; the scale
    is the max over the window, so an early outlier ages out."""
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = attrs.get("bit_length", 8)
    window = attrs.get("window_size", 10000)
    is_test = attrs.get("is_test", False) or ctx.is_test
    cur = jnp.max(jnp.abs(x))
    if ins.get("InScales"):
        buf = ins["InScales"][0].reshape(-1)
        it = ins["Iter"][0].reshape(()).astype(jnp.int32) if ins.get("Iter") else jnp.int32(0)
        new_buf = jnp.where(is_test, buf, buf.at[it % buf.shape[0]].set(cur))
        scale = jnp.where(is_test, in_scale, jnp.max(new_buf))
        return {
            "Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [jax.lax.stop_gradient(scale.reshape(1))],
            "OutScales": [jax.lax.stop_gradient(new_buf)],
        }
    scale = jnp.where(is_test, in_scale, jnp.maximum(cur, in_scale))
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [jax.lax.stop_gradient(scale.reshape(1))]}


@register("fake_quantize_moving_average_abs_max", no_grad_inputs=("InScale", "InAccum", "InState"))
def _fake_quantize_moving_average_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    cur = jnp.max(jnp.abs(x))
    state = ins["InState"][0].reshape(()) if ins.get("InState") else jnp.asarray(1.0)
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else in_scale
    new_state = jnp.where(is_test, state, rate * state + 1.0)
    new_accum = jnp.where(is_test, accum, rate * accum + cur)
    scale = jnp.where(is_test, in_scale, new_accum / new_state)
    return {
        "Out": [_quant_dequant(x, scale, bits)],
        "OutScale": [jax.lax.stop_gradient(scale.reshape(1))],
        "OutState": [jax.lax.stop_gradient(new_state.reshape(1))],
        "OutAccum": [jax.lax.stop_gradient(new_accum.reshape(1))],
    }


@register("fake_channel_wise_quantize_abs_max")
def _fake_channel_wise_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    axes = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=axes)
    shp = [-1] + [1] * (x.ndim - 1)
    out = _quant_dequant(x, scale.reshape(shp), bits)
    return {"Out": [out], "OutScale": [jax.lax.stop_gradient(scale)]}


@register("fake_dequantize_max_abs", no_grad_inputs=("Scale",))
def _fake_dequantize_max_abs(ctx, ins, attrs):
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = attrs.get("max_range", 127.0)
    return {"Out": [x * scale.reshape(()) / max_range]}
