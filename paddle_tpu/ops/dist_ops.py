"""Distributed op lowerings: send / recv / barriers / prefetch.

The reference implements these as side-effecting runtime ops over gRPC
(operators/distributed_ops/send_op.cc, recv_op.cc, send_barrier_op.cc,
fetch_barrier_op.cc, prefetch_op.cc).  Here the whole training step is one
XLA executable, so the DCN control plane rides **ordered host callbacks**
(`jax.experimental.io_callback(ordered=True)`): XLA sequences them with the
surrounding compute, giving exactly the reference's op-order semantics
(grads computed → send → send_barrier → recv updated params →
fetch_barrier) without leaving the compiled step.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from .common import jdt
from ..core.registry import register


def _client(ep, trainer_id=None):
    from .. import distributed
    from ..distributed.rpc import RPCClient

    if trainer_id is not None:
        distributed._note_endpoint(ep, trainer_id)
    return RPCClient.get(ep)


def _check_not_evicted(result, ep, trainer_id):
    """A pserver answers evicted=True to a trainer it declared dead (its
    grads were dropped mid-round).  Training on silently-stale params
    would diverge without a trace — fail fast and loudly instead."""
    if isinstance(result, dict) and result.get("evicted"):
        raise RuntimeError(
            "trainer %s was evicted by pserver %s (missed the liveness "
            "deadline); its sync round moved on without it — restart the "
            "trainer to rejoin" % (trainer_id, ep))


@register("send", side_effect=True)
def _send(ctx, ins, attrs):
    """Split X flat into `sections`, ship block i to epmap[i] as
    block_names[i].  One send op per original grad var."""
    sections = [int(s) for s in attrs["sections"]]
    epmap = list(attrs["epmap"])
    block_names = list(attrs["block_names"])
    trainer_id = int(attrs.get("trainer_id", 0))

    def host_send(x):
        flat = np.asarray(x).reshape(-1)
        off = 0
        for sec, ep, bname in zip(sections, epmap, block_names):
            r = _client(ep, trainer_id).send_var(
                bname, flat[off : off + sec], trainer_id)
            _check_not_evicted(r, ep, trainer_id)
            off += sec
        return np.int32(0)

    tok = io_callback(
        host_send, jax.ShapeDtypeStruct((), jnp.int32), ins["X"][0], ordered=True
    )
    return {"Out": [tok]}


@register("send_barrier", side_effect=True)
def _send_barrier(ctx, ins, attrs):
    endpoints = list(attrs["endpoints"])
    trainer_id = int(attrs.get("trainer_id", 0))

    def host_barrier():
        for ep in endpoints:
            r = _client(ep).barrier("send", trainer_id)
            _check_not_evicted(r, ep, trainer_id)
        return np.int32(0)

    tok = io_callback(host_barrier, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": [tok]}


@register("recv", side_effect=True)
def _recv(ctx, ins, attrs):
    """Gather param blocks from epmap, concat + reshape to the param."""
    sections = [int(s) for s in attrs["sections"]]
    epmap = list(attrs["epmap"])
    block_names = list(attrs["block_names"])
    shape = [int(s) for s in attrs["shape"]]
    dtype = jdt(attrs.get("dtype", "float32"))
    trainer_id = int(attrs.get("trainer_id", 0))

    def host_recv():
        parts = [
            np.asarray(_client(ep).get_var(bname, trainer_id)).reshape(-1)
            for ep, bname in zip(epmap, block_names)
        ]
        out = np.concatenate(parts).reshape(shape)
        return out.astype(np.dtype(dtype.name if hasattr(dtype, "name") else dtype))

    out = io_callback(
        host_recv, jax.ShapeDtypeStruct(tuple(shape), dtype), ordered=True
    )
    return {"Out": [out]}


@register("fetch_barrier", side_effect=True)
def _fetch_barrier(ctx, ins, attrs):
    endpoints = list(attrs["endpoints"])
    trainer_id = int(attrs.get("trainer_id", 0))

    def host_barrier():
        for ep in endpoints:
            _client(ep).barrier("fetch", trainer_id)
        return np.int32(0)

    tok = io_callback(host_barrier, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": [tok]}


@register("prefetch", no_grad_inputs={"Ids"}, side_effect=True)
def _prefetch(ctx, ins, attrs):
    """Distributed embedding lookup (prefetch_op / split_ids / merge_ids
    analog): route each id to server id%nservers, fetch rows, merge back
    in input order.  Fixed id-array shape keeps XLA happy; routing is
    host-side."""
    ids = ins["Ids"][0]
    epmap = list(attrs["epmap"])
    table_names = list(attrs["table_names"])
    emb_dim = int(attrs["emb_dim"])
    trainer_id = int(attrs.get("trainer_id", 0))
    n = len(epmap)

    id_shape = tuple(ids.shape)
    out_shape = id_shape + (emb_dim,)

    def host_prefetch(ids_v):
        flat = np.asarray(ids_v).reshape(-1).astype(np.int64)
        out = np.zeros((flat.size, emb_dim), dtype=np.float32)
        for s in range(n):
            mask = (flat % n) == s
            if not mask.any():
                continue
            local = flat[mask] // n
            rows = np.asarray(
                _client(epmap[s], trainer_id).prefetch(
                    table_names[s], local, trainer_id
                )
            )
            out[mask] = rows
        return out.reshape(out_shape)

    out = io_callback(
        host_prefetch,
        jax.ShapeDtypeStruct(out_shape, jnp.float32),
        ids,
        ordered=True,
    )
    return {"Out": [out]}


@register("send_sparse", no_grad_inputs={"Ids"}, side_effect=True)
def _send_sparse(ctx, ins, attrs):
    """Push sparse embedding grads (SelectedRows semantics): rows keyed by
    Ids go back to their owning server — applied at the round barrier in
    sync mode, immediately in async (see ps_server._h_send_sparse)."""
    ids, grad = ins["Ids"][0], ins["Grad"][0]
    epmap = list(attrs["epmap"])
    table_names = list(attrs["table_names"])
    trainer_id = int(attrs.get("trainer_id", 0))
    scale = float(attrs.get("scale", 1.0))
    n = len(epmap)

    def host_push(ids_v, grad_v):
        flat = np.asarray(ids_v).reshape(-1).astype(np.int64)
        g = np.asarray(grad_v).reshape(flat.size, -1) * scale
        for s in range(n):
            mask = (flat % n) == s
            if not mask.any():
                continue
            local = flat[mask] // n
            r = _client(epmap[s], trainer_id).send_sparse(
                table_names[s], local, g[mask], trainer_id
            )
            _check_not_evicted(r, epmap[s], trainer_id)
        return np.int32(0)

    tok = io_callback(
        host_push, jax.ShapeDtypeStruct((), jnp.int32), ids, grad, ordered=True
    )
    return {"Out": [tok]}


@register("checkpoint_notify", side_effect=True)
def _checkpoint_notify(ctx, ins, attrs):
    """distributed_ops/checkpoint_notify_op.cc: in-program trigger asking
    every pserver in `epmap` to snapshot its shard into `dir` (host
    callback, ordered with the surrounding sends/barriers)."""
    epmap = list(attrs.get("epmap", []))
    ckpt_dir = attrs.get("dir") or None
    trainer_id = int(attrs.get("trainer_id", 0))

    def host_notify():
        for ep in epmap:
            _client(ep, trainer_id).checkpoint_notify(
                dir=ckpt_dir, trainer_id=trainer_id)
        return np.int32(0)

    tok = io_callback(
        host_notify, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": [tok]}


@register("ref_by_trainer_id", no_grad_inputs=("TrainerId",))
def _ref_by_trainer_id(ctx, ins, attrs):
    """distributed_ops/ref_by_trainer_id_op.h: select X[trainer_id] from
    the input list.  The trainer id is a host-known scalar in every real
    program (wired by the transpiler from the env contract), so the
    selection happens at trace time when possible; a traced id falls back
    to lax.switch over the (equal-shaped) candidates."""
    import jax.core

    xs = ins["X"]
    tid = ins["TrainerId"][0]
    if not isinstance(tid, jax.core.Tracer):
        idx = int(np.asarray(tid).reshape(-1)[0])
        if idx < 0 or idx >= len(xs):
            raise IndexError(
                "ref_by_trainer_id: trainer id %d out of range (%d inputs)"
                % (idx, len(xs)))
        return {"Out": [xs[idx]]}
    import jax.lax as lax

    return {"Out": [lax.switch(
        jnp.clip(tid.reshape(()).astype(jnp.int32), 0, len(xs) - 1),
        [lambda i=i: xs[i] for i in range(len(xs))])]}
