"""Distributed op lowerings: send / recv / barriers / prefetch.

The reference implements these as side-effecting runtime ops over gRPC
(operators/distributed_ops/send_op.cc, recv_op.cc, send_barrier_op.cc,
fetch_barrier_op.cc, prefetch_op.cc).  Here the whole training step is one
XLA executable, so the DCN control plane rides **ordered host callbacks**
(`jax.experimental.io_callback(ordered=True)`): XLA sequences them with the
surrounding compute, giving exactly the reference's op-order semantics
(grads computed → send → send_barrier → recv updated params →
fetch_barrier) without leaving the compiled step.
"""

import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from .common import jdt
from ..core.registry import register

_null_ctx = contextlib.nullcontext


def _client(ep, trainer_id=None):
    from .. import distributed
    from ..distributed.rpc import RPCClient

    if trainer_id is not None:
        distributed._note_endpoint(ep, trainer_id)
    return RPCClient.get(ep)


def _client_map(trainer_id):
    """Per-op client memo: each lowering's host callback runs once per
    STEP per VARIABLE, and `_client` re-takes the registry lock and
    re-fires the endpoint/heartbeat registration every call.  The memo
    resolves each endpoint once per op (lazily, at the first step, when
    the servers are definitely up) and hands back the cached client from
    then on — the per-step hot path is one dict hit."""
    cache = {}

    def get(ep):
        cli = cache.get(ep)
        if cli is None:
            cli = cache[ep] = _client(ep, trainer_id)
        return cli

    return get


def _rank_clients(eps):
    """Client memo for COLLECTIVE-mode sparse ops, keyed (endpoint,
    rank): the logical trainer id is the mesh replica's axis_index — a
    runtime value — so registration/heartbeat wiring happens inside the
    host callback, once per (endpoint, rank).  The first call registers
    this rank with EVERY endpoint (not just the ones its ids happen to
    route to): the pserver's serve loop waits for a `complete` from each
    registered rank, and a rank whose ids never hashed to some server
    would otherwise leave that server waiting forever."""
    cache = {}

    def get(ep, rank):
        key = (ep, int(rank))
        cli = cache.get(key)
        if cli is None:
            from ..distributed.rpc import RPCClient

            if not any(k[1] == int(rank) for k in cache):
                from .. import distributed

                for e in eps:
                    distributed._note_endpoint(e, int(rank))
            cli = cache[key] = RPCClient.get(ep)
        return cli

    return get


def _replica_rank(fallback_id):
    """Traced mesh rank of the current replica for collective-mode rpc
    ops: lax.axis_index when the collective trace bound an axis, else the
    static trainer id (single-replica degradation)."""
    from ..parallel.collective import lowering_axis

    bound = lowering_axis()
    return (jax.lax.axis_index(bound[0]) if bound is not None
            else jnp.int32(fallback_id))


def _pipelined(trainer_id):
    """Like _client_map but for the windowed in-flight client (bucketed
    sends/gets); endpoint registration still runs once so completes and
    heartbeats cover pipelined-only endpoints."""
    from .. import distributed
    from ..distributed.rpc import PipelinedClient

    cache = {}

    def get(ep):
        cli = cache.get(ep)
        if cli is None:
            distributed._note_endpoint(ep, trainer_id)
            cli = cache[ep] = PipelinedClient.get(ep)
        return cli

    return get


# blocking verbs (sync-mode gets, barriers) wait on cluster progress, not
# network latency — mirror RPCClient.barrier_timeout for pipelined calls
_BLOCKING_TIMEOUT = 1200.0


# ---- incarnation fencing (sync bucketed path) ---------------------------
# Per-endpoint replay state (docs/FAULT_TOLERANCE.md): every reply
# envelope carries the pserver's incarnation; a CHANGE between the
# incarnation this round's sends landed on and a later observation means
# the server restarted mid-round from its round-boundary checkpoint, so
# the current round's bucket stream (sparse chunks first, then dense
# buckets — the last dense bucket is the folded barrier) is re-shipped.
# Server-side set counting + the checkpointed fold fence make the replay
# idempotent: rounds the restored snapshot already contains are dropped,
# rounds it missed are re-assembled exactly once.  Ordered host callbacks
# mean one thread mutates this state; the dict is module-level so the
# send ops (which record) and recv_bucket (which detects and replays)
# share it.
_fences = {}  # endpoint -> {"inc", "step", "fstep", "sends", "sparse"}
_MAX_ROUND_REPLAYS = 6

# ---- async fenced delivery (durable async sparse) -----------------------
# Per-endpoint client state for ASYNC mode (transpiler-stamped
# async_fence attr): every send_sparse chunk carries a per-(table)
# sequence token (minted once per STEP — empty chunks ship too, so the
# seq doubles as this trainer's logical clock at every server), the
# server acks the highest durably-applied seq, un-acked chunks sit in a
# bounded resend queue, and an observed incarnation bump re-ships them
# before any new traffic — at-least-once delivery that the server's seq
# fence + write-ahead journal turn into exactly-once across SIGKILL
# (docs/FAULT_TOLERANCE.md).  Dense async buckets carry their own aseq
# token and sit in their own bounded resend queue (udense) until the
# drained reply's dense_acked high-water prunes them: restart
# re-delivery rides the RPC-layer retry (deduped by the server fence),
# and a PLAN FLIP (live shard migration) re-ships exactly the buckets
# the old owner dropped as stale — regrouped under the new dispatch, so
# a mid-flip restart loses zero acked-but-unapplied dense updates (the
# former sparse-only known limit, closed).
_ASYNC_RESEND_MAX = 256


def _async_st(ep):
    """Async keys of the per-endpoint fence state (lazily added so the
    sync-path state dicts stay unchanged)."""
    st = _fence(ep)
    if "sseq" not in st:
        st["ainc"] = None   # async incarnation baseline
        st["aseq"] = 0      # dense async bucket seq
        st["sseq"] = {}     # table -> last minted sparse seq
        st["unacked"] = {}  # table -> {seq: send_sparse kwargs}
        st["udense"] = {}   # aseq -> un-acked dense bucket blocks
        st["adropped"] = set()  # aseqs the server dropped as stale_plan
    return st


def _async_note_ack(st, table, reply):
    """Prune the resend queue up to the server's acked high-water; a
    `dup` reply is a witnessed exactly-once drop (counted)."""
    if not isinstance(reply, dict):
        return
    from ..distributed import rpc as _rpc

    if reply.get("dup"):
        _rpc.note_async(async_dedup_drops=1)
    acked = reply.get("acked")
    if acked is None:
        return
    uq = st["unacked"].get(table)
    if uq:
        for seq in [s for s in uq if s <= int(acked)]:
            del uq[seq]


def _async_check_replay(cli, ep, trainer_id):
    """Observed incarnation bump: the pserver restarted — re-ship every
    un-acked chunk (in seq order) before any new traffic, so nothing the
    dead incarnation only held in memory is silently lost.  The server's
    monotonic seq fence absorbs re-delivery of anything its journal
    replay already restored (`dup`)."""
    from ..distributed import rpc as _rpc

    st = _async_st(ep)
    cur = _rpc.incarnation_of(ep)
    if st["ainc"] is None:
        st["ainc"] = cur
        return
    if cur is None or cur == st["ainc"]:
        return
    import time

    t0 = time.perf_counter()
    n = 0
    for table in sorted(st["unacked"]):
        for seq in sorted(st["unacked"][table]):
            kw = st["unacked"][table].get(seq)
            if kw is None:
                continue
            r = cli.call("send_sparse", **kw)
            _check_not_evicted(r, ep, trainer_id)
            _async_note_ack(st, table, r)
            n += 1
    st["ainc"] = cur
    if n:
        _rpc.note_async(async_resends=n)
        _rpc.note_recovery((time.perf_counter() - t0) * 1e3)


# ---- trainer-side hot-row cache (FLAGS_sparse_hot_rows) -----------------
class HotRowCache:
    """LRU row cache for ASYNC distributed-lookup prefetch: hits skip the
    prefetch RPC entirely.  Pushed grads MIRROR the server's sgd apply on
    the cached copy (duplicates merged exactly like
    ps_server._apply_sparse, so a single-trainer cached run matches the
    cache-off run bit for bit between refreshes), and every entry is
    re-fetched after `ttl` steps so multi-trainer drift is corrected
    instead of accumulating.  The refresh keeps a per-row RESIDUAL — the
    drift other trainers contributed over the last window (the PR 5
    `_ef_residuals` discipline, keyed per row instead of per block) —
    and feeds `residual/ttl` forward into each mirrored step as a
    predictor, so steady cross-trainer traffic is tracked between
    refreshes rather than ignored until the next one."""

    def __init__(self, capacity, ttl, lr):
        from collections import OrderedDict

        self.capacity = int(capacity)
        self.ttl = max(1, int(ttl))
        self.lr = float(lr)
        self.rows = OrderedDict()  # gid -> [row, expire_step]
        self.residuals = {}        # gid -> drift observed at last refresh
        self.step = 0
        self.hits = 0
        self.misses = 0

    def tick(self):
        self.step += 1

    def lookup(self, gids):
        """Returns (hit dict gid->row copy, miss mask over gids)."""
        hits = {}
        miss = np.ones(len(gids), bool)
        for i, g in enumerate(gids):
            g = int(g)
            ent = self.rows.get(g)
            if ent is not None and ent[1] > self.step:
                self.rows.move_to_end(g)
                hits[g] = ent[0]
                miss[i] = False
        self.hits += len(gids) - int(miss.sum())
        self.misses += int(miss.sum())
        return hits, miss

    def insert(self, gids, rows):
        """Fresh server truth: correct the mirror, record the drift
        residual (truth - local estimate) for the predictor, rearm TTL.
        A gid repeated within one miss batch records its residual ONCE —
        the second occurrence carries the same truth the first just
        stored, and `truth - truth = 0` would wipe the predictor for
        exactly the hottest rows."""
        seen = set()
        for g, row in zip(gids, rows):
            g = int(g)
            row = np.array(row)
            if g not in seen:
                seen.add(g)
                ent = self.rows.get(g)
                if ent is not None:
                    self.residuals[g] = row - ent[0]
            self.rows[g] = [row, self.step + self.ttl]
            self.rows.move_to_end(g)
        while len(self.rows) > self.capacity:
            old, _ = self.rows.popitem(last=False)
            self.residuals.pop(old, None)

    def push(self, gids, grads):
        """Mirror one push on the cached copies: merged duplicate ids,
        row -= lr * g (+ the drift predictor) — sgd-exact locally."""
        gids = np.asarray(gids).reshape(-1)
        grads = np.asarray(grads).reshape(gids.size, -1)
        uids, inv = np.unique(gids, return_inverse=True)
        merged = np.zeros((uids.size, grads.shape[1]), grads.dtype)
        np.add.at(merged, inv, grads)
        for g, gm in zip(uids, merged):
            ent = self.rows.get(int(g))
            if ent is None:
                continue
            dt = ent[0].dtype
            # compute wide, round back to the row dtype — the exact
            # rounding numpy's in-place f32 apply does server-side, so
            # the sgd mirror stays bit-identical between refreshes
            row = np.asarray(ent[0] - self.lr * gm, dtype=dt)
            res = self.residuals.get(int(g))
            if res is not None:
                row = np.asarray(row + res / float(self.ttl), dtype=dt)
            ent[0] = row


_hot_caches = {}  # tuple(table_names) -> HotRowCache


def _hot_cache_for(table_names, hot_opt):
    """Resolve (or build) the cache shared by a table's prefetch and
    send_sparse ops.  None when disabled: flag off, non-sgd optimizer,
    or a scheduled lr the client cannot mirror."""
    from ..flags import get_flag

    cap = int(get_flag("sparse_hot_rows"))
    if cap <= 0 or not hot_opt or hot_opt.get("type") != "sgd" \
            or hot_opt.get("lr") is None:
        return None
    key = tuple(table_names)
    cache = _hot_caches.get(key)
    if cache is None:
        cache = _hot_caches[key] = HotRowCache(
            cap, int(get_flag("sparse_hot_ttl")), float(hot_opt["lr"]))
    return cache

# ---- elastic autoscaling: runtime re-derivable plans --------------------
# The transpiler stamps every bucket/sparse rpc op with a JSON-able
# plan SPEC (a pure function input: param set, endpoints, world size,
# flags) plus a plan group id.  At runtime the ops keep ONE shared plan
# state per group: when a pserver reply reveals a newer PLAN EPOCH (the
# server minted one at a round boundary after its live set changed
# durably), the next step re-derives the whole plan from the spec via
# transpiler.derive_plan for the new world — bit-identical to the
# transpile-time plan when the world is unchanged — and corrects the
# program-baked 1/N grad scale by a host-side factor N0/N_live.  Frames
# carry the sender's epoch; the server fences stale-epoch frames like
# stale incarnations and the sender re-plans + re-ships
# (docs/FAULT_TOLERANCE.md "Elastic autoscaling").
_plans = {}  # plan_gid -> runtime plan state


def _plan_rt(attrs):
    """Shared runtime plan state for this op's plan group (None when
    the op predates the plan spec, or FLAGS_elastic_replan is off —
    legacy static-plan behavior, bit for bit)."""
    gid = attrs.get("plan_gid")
    spec = attrs.get("plan_spec")
    if gid is None or not spec:
        return None
    from ..flags import get_flag

    if not get_flag("elastic_replan"):
        return None
    st = _plans.get(gid)
    if st is None:
        base = int(spec["trainers"])
        st = _plans[gid] = {
            "spec": spec, "epoch": 0, "base": base, "world": base,
            "corr": 1.0, "derived": None, "replans": 0}
    return st


def _plan_eps_now(st, fallback):
    """The endpoint set the CURRENT plan dispatches over: the derived
    plan's (live pserver migration moves it), else the transpile-time
    list."""
    if st is not None and st.get("derived") is not None:
        return [str(e) for e in st["derived"]["endpoints"]]
    return list(fallback)


def _sparse_route(st, s, fallback):
    """The endpoint owning sparse shard s under the CURRENT plan: rows
    hash to their stable BASE shard (g % n_base) forever; live pserver
    migration only moves which endpoint serves the shard."""
    if st is not None and st.get("derived") is not None:
        se = st["derived"].get("sparse_eps") or []
        if s < len(se):
            return str(se[s])
    return fallback[s]


def _move_async_sparse_state(old_ep, new_ep, table):
    """Live pserver migration moved a sparse shard: carry the client's
    per-(endpoint, table) async fence bookkeeping — the minted seq
    counter and the un-acked resend queue — to the shard's new owner,
    whose server-side (trainer, table) fence arrived with the migrated
    state, so seq continuity (and exactly-once) holds across the
    move."""
    old_st, new_st = _async_st(old_ep), _async_st(new_ep)
    if table in old_st["sseq"]:
        new_st["sseq"][table] = max(new_st["sseq"].get(table, 0),
                                    old_st["sseq"].pop(table))
    uq = old_st["unacked"].pop(table, None)
    if uq:
        new_st["unacked"].setdefault(table, {}).update(uq)


def _maybe_replan(st, eps, trainer_id):
    """Re-derive the plan if any endpoint's observed plan epoch moved
    past ours: ONE `plan` handshake fetches the new world — trainer
    count AND pserver endpoint set (live shard migration moves the
    latter) — derive_plan rebuilds the bucket layout from the spec, and
    the scale correction becomes N0/N_live.  Runs at the top of every
    send host callback — a dict compare when nothing changed."""
    if st is None:
        return
    from ..distributed import rpc as _rpc

    newest, target = st["epoch"], None
    for ep in eps:
        pe = _rpc.plan_epoch_of(ep)
        if pe > newest:
            newest, target = pe, ep
    if target is None:
        return
    import time

    from ..distributed.rpc import RPCClient
    from ..transpiler.distribute_transpiler import derive_plan

    t0 = time.perf_counter()
    r = RPCClient.get(target).call("plan", trainer_id=int(trainer_id))
    epoch = int(r.get("epoch", newest))
    world = max(1, int(r.get("world", st["world"])))
    ps_eps = [str(e) for e in (r.get("endpoints") or [])]
    prev_eps = _plan_eps_now(st, st["spec"]["endpoints"])
    st["derived"] = derive_plan(
        st["spec"],
        world={"trainers": world, "endpoints": ps_eps or None})
    st["epoch"] = max(newest, epoch)
    st["world"] = world
    # a changed PSERVER set invalidates the recorded per-endpoint round
    # layout: the stale-plan recovery must REBUILD the round from its
    # recorded raw blocks under the new dispatch, not re-ship in place
    st["relayout"] = bool(
        ps_eps and set(ps_eps) != set(prev_eps)) or st.get("relayout",
                                                           False)
    st["corr"] = float(st["base"]) / float(world)
    st["replans"] += 1
    _rpc.note_async(replans=1,
                    replan_ms=round((time.perf_counter() - t0) * 1e3, 3))
    print("TRAINER REPLAN epoch=%d world=%d corr=%.6g eps=%d"
          % (st["epoch"], world, st["corr"],
             len(_plan_eps_now(st, st["spec"]["endpoints"]))),
          flush=True)


def _note_plan(ep, result):
    from ..distributed import rpc as _rpc

    _rpc.note_plan_reply(ep, result)


def _scale_corr(arr, corr):
    """Host-side elastic grad-scale correction: the program bakes 1/N0,
    the live world is N_live — multiply by N0/N_live in the arr's own
    dtype.  corr == 1.0 skips entirely, keeping the unchanged-world
    path bit-identical to the static plan."""
    if corr == 1.0 or arr.dtype.kind != "f":
        return arr
    return (arr * arr.dtype.type(corr)).astype(arr.dtype, copy=False)


def _drain_plan_checked(pipe, ep, trainer_id, stale_plan=None):
    """Window drain + the three reply inspections every drained result
    needs: eviction is fatal, pepoch feeds the plan registry, and a
    stale_plan notice (the server fenced our frames — our world is out
    of date) is collected for the caller's re-plan + re-ship."""
    results = pipe(ep).drain()
    for r in results:
        _check_not_evicted(r, ep, trainer_id)
        _note_plan(ep, r)
        if not isinstance(r, dict):
            continue
        da = r.get("dense_acked")
        if da is not None:
            # dense ack high-water: prune the async dense resend queue
            # (contiguous fence only — an applied-ahead-of-a-gap bucket
            # stays queued; re-delivery is deduped server-side)
            ud = _async_st(ep)["udense"]
            for q in [q for q in ud if q <= int(da)]:
                del ud[q]
        if stale_plan is not None and r.get("stale_plan"):
            stale_plan.add(ep)
            if r.get("dropped_aseq") is not None:
                _async_st(ep)["adropped"].add(int(r["dropped_aseq"]))
    return results


def _async_replay_dense(pipe, plan_rt, trainer_id, stale_eps):
    """Plan-flip dense re-ship (closes the PR 15 known limit: only
    sparse chunks survived a flip).  For each stale-fenced endpoint,
    every aseq the server REPORTED dropped (adropped — never an
    applied-but-unacked one, which would double-apply under a fresh
    aseq) re-ships from the udense record: its blocks regroup by their
    NEW owner under the freshly derived plan.  The group staying on the
    old endpoint keeps the ORIGINAL aseq — it fills the fence hole the
    drop left, unsticking the contiguous ack high-water for both sides
    — while groups for other owners mint fresh aseqs on those streams.
    Every re-shipped bucket re-enters its target's udense, so a crash
    mid-recovery re-delivers and the fences dedup."""
    from ..distributed import rpc as _rpc

    derived = plan_rt.get("derived") if plan_rt else None
    owner = {}
    for ep, entries in (derived["send_buckets"] if derived else []):
        for _xi, _b, _e, bn in entries:
            owner[str(bn)] = str(ep)
    n = 0
    for old_ep in sorted(stale_eps):
        st = _async_st(old_ep)
        dropped = sorted(q for q in st["adropped"] if q in st["udense"])
        st["adropped"].clear()
        for q in dropped:
            blocks = st["udense"].pop(q)
            regroup = {}
            for bn, v in blocks.items():
                regroup.setdefault(owner.get(str(bn), old_ep),
                                   {})[bn] = v
            # the old endpoint's group ships even when EMPTY: the
            # no-op bucket commits aseq q there, filling the hole
            regroup.setdefault(old_ep, {})
            for new_ep in sorted(regroup):
                blk = regroup[new_ep]
                nst = _async_st(new_ep)
                if new_ep == old_ep:
                    aseq = q
                else:
                    nst["aseq"] += 1
                    aseq = nst["aseq"]
                nst["udense"][aseq] = blk
                pipe(new_ep).submit("send_bucket", blocks=blk,
                                    trainer_id=trainer_id,
                                    seq_total=None, aseq=aseq)
                n += 1
    if n:
        _rpc.note_async(async_dense_resends=n)
        print("TRAINER DENSE RESEND buckets=%d eps=%d"
              % (n, len(stale_eps)), flush=True)
    return n


def _wrap_rows_wire(rows, wire_dtype):
    """Sparse row values onto the planned wire (the send_sparse wrap,
    shared with the replay's re-compression)."""
    rows = np.asarray(rows)
    if wire_dtype != "bfloat16" or rows.dtype.kind != "f" \
            or not rows.size:
        return rows
    from ..distributed import rpc as _rpc

    return _rpc.Bf16Wire(rows)


def _plan_wire(st):
    flags = (st["spec"].get("flags") or {}) if st else {}
    return (str(flags.get("comm_wire_dtype") or "float32"),
            bool(flags.get("comm_grad_int8")))


def _replay_round_plan(pipe, trainer_id, eps, st, stale_plan=None):
    """Stale-plan recovery: re-stamp the recorded round stream with the
    freshly re-derived epoch, rescale it from the recorded corr to the
    current one, then re-ship through the SAME skeleton the incarnation
    replay uses (_replay_round_sends: sparse first, dense submits,
    inspected drains) — one re-ship path to keep correct, and a SECOND
    epoch mint landing mid-recovery surfaces in the caller's
    `stale_plan` set instead of being silently swallowed.

    EXACT transition round (closes the PR 10 documented gap): wire-
    compressed blocks re-compress from their recorded PRE-compression
    raw values after the rescale — compress(raw * ratio) on the wire,
    never rescaled-compressed bytes, under bf16 and int8 alike (the
    int8 error-feedback residual is re-derived from the replacing
    quantization).  When the PSERVER SET changed (live shard
    migration), the recorded per-endpoint layout matches no current
    dispatch: the round REBUILDS from the recorded raw blocks under the
    derived plan instead (_rebuild_round_plan)."""
    if st.get("relayout") and st.get("derived") is not None:
        _rebuild_round_plan(pipe, trainer_id, st, stale_plan)
        return
    wire_dtype, grad_int8 = _plan_wire(st)
    for ep in eps:
        fst = _fence(ep)
        rec_corr = float(fst.get("corr", 1.0))
        ratio = st["corr"] / rec_corr if rec_corr else 1.0
        for table, kw in fst["sparse"].items():
            kw["pepoch"] = st["epoch"]
            raw = (fst.get("sparse_raw") or {}).get(table)
            if raw is not None:
                raw = _scale_corr(np.asarray(raw), ratio)
                fst.setdefault("sparse_raw", {})[table] = raw
                kw["rows"] = _wrap_rows_wire(raw, wire_dtype)
            elif isinstance(kw.get("rows"), np.ndarray):
                kw["rows"] = _scale_corr(kw["rows"], ratio)
        for kw in fst["sends"]:
            kw["pepoch"] = st["epoch"]
            newb = {}
            for bn, v in kw["blocks"].items():
                raw = (fst.get("raw") or {}).get(bn)
                if raw is not None:
                    raw = _scale_corr(np.asarray(raw), ratio)
                    fst.setdefault("raw", {})[bn] = raw
                    newb[bn] = _recompress_block(ep, bn, raw,
                                                 wire_dtype, grad_int8)
                elif isinstance(v, np.ndarray):
                    newb[bn] = _scale_corr(v, ratio)
                else:
                    newb[bn] = v  # pre-raw-era record: ship as recorded
            kw["blocks"] = newb
        fst["corr"] = st["corr"]
    _replay_round_sends(pipe, trainer_id, eps, stale_plan)


def _rebuild_round_plan(pipe, trainer_id, st, stale_plan=None):
    """The pserver SET changed mid-round (live shard migration): the
    recorded per-endpoint stream no longer matches any server's
    dispatch.  Rebuild the round from the recorded raw blocks under the
    freshly derived plan — re-bucketed per the NEW block->endpoint map,
    rescaled exactly, re-compressed fresh — and ship it to the new
    owners.  Per-trainer fold fences make this exactly-once: an owner
    that already folded this step (its kept blocks) drops the re-ship
    as dup_round, while shards that moved carry their pre-capture
    applies inside the migrated state."""
    d = st["derived"]
    wire_dtype, grad_int8 = _plan_wire(st)
    compressing = grad_int8 or wire_dtype != "float32"
    # gather the recorded round across EVERY endpoint's fence record —
    # CURRENT round only (max step token): an endpoint dropped from the
    # dispatch by an earlier migration may still hold a stale record,
    # and mixing rounds would re-ship old grads as new
    rstep = max([int(f.get("step", 0)) for f in _fences.values()
                 if f.get("sends") or f.get("sparse")] or [0])
    blocks_raw, sparse_recs, rec_corr = {}, {}, None
    for ep, fst in sorted(_fences.items()):
        if not (fst.get("sends") or fst.get("sparse")) \
                or int(fst.get("step", 0)) != rstep:
            continue
        for kw in fst.get("sends") or []:
            for bn, v in kw["blocks"].items():
                raw = (fst.get("raw") or {}).get(bn)
                val = raw if raw is not None else v
                if not isinstance(val, np.ndarray):
                    raise RuntimeError(
                        "cannot rebuild the transition round: block %r "
                        "was recorded wire-compressed without its raw "
                        "value (a pre-raw-era record) and the pserver "
                        "set changed" % bn)
                blocks_raw[bn] = np.asarray(val)
        for table, kw in (fst.get("sparse") or {}).items():
            sparse_recs[table] = (
                dict(kw),
                (fst.get("sparse_raw") or {}).get(table),
                (fst.get("sparse_idx") or {}).get(table),
                ep)
        if rec_corr is None:
            rec_corr = float(fst.get("corr", 1.0)) or 1.0
    if not blocks_raw and not sparse_recs:
        st["relayout"] = False
        return
    ratio = st["corr"] / (rec_corr or 1.0)
    new_eps = [str(e) for e in d["endpoints"]]
    totals = {ep: int(n) for ep, n in (d.get("sync_totals")
                                       or {}).items()}
    # reset every fence record, then rebuild per the new dispatch with a
    # UNIFORM step token (per-endpoint step counters advance in
    # lockstep; a fresh endpoint adopts the round's token)
    for fst in _fences.values():
        fst["sends"] = []
        fst["sparse"] = {}
        fst["sparse_raw"] = {}
        fst["raw"] = {}
        fst.pop("sparse_step", None)
    declared = {}  # ep -> [table, ...]
    for table, (kw, raw, sidx, rec_ep) in sorted(sparse_recs.items()):
        ep = (str(d["sparse_eps"][sidx])
              if sidx is not None and sidx < len(d.get("sparse_eps", []))
              else rec_ep)
        fst = _fence(ep)
        fst["step"] = rstep
        kw = dict(kw, pepoch=st["epoch"], step=rstep)
        if raw is not None:
            raw = _scale_corr(np.asarray(raw), ratio)
            fst.setdefault("sparse_raw", {})[table] = raw
            kw["rows"] = _wrap_rows_wire(raw, wire_dtype)
        elif isinstance(kw.get("rows"), np.ndarray):
            kw["rows"] = _scale_corr(kw["rows"], ratio)
        fst["sparse_step"] = rstep
        fst["sparse"][table] = kw
        fst.setdefault("sparse_idx", {})[table] = sidx
        declared.setdefault(ep, []).append(table)
    per_ep = {}
    for ep, entries in d["send_buckets"]:
        blocks = {}
        fst = _fence(ep)
        for xi, b, e, bn in entries:
            raw = blocks_raw.get(bn)
            if raw is None:
                continue  # an empty-bucket barrier entry
            raw = _scale_corr(raw, ratio)
            fst.setdefault("raw", {})[bn] = raw
            blocks[bn] = (_recompress_block(ep, bn, raw, wire_dtype,
                                            grad_int8)
                          if compressing else raw)
        per_ep.setdefault(str(ep), []).append(blocks)
    for ep, blist in sorted(per_ep.items()):
        fst = _fence(ep)
        fst["step"] = rstep
        fst["corr"] = st["corr"]
        fst["sends"] = [
            dict(blocks=blocks, trainer_id=trainer_id,
                 seq_total=totals.get(ep), step=rstep, seq_idx=i,
                 sparse_tables=sorted(declared.get(ep, [])),
                 pepoch=st["epoch"])
            for i, blocks in enumerate(blist)]
    st["relayout"] = False
    # a grown pserver is contacted here for the first time: register +
    # heartbeat + complete coverage must start before its first frame
    from .. import distributed

    for ep in new_eps:
        distributed._note_endpoint(ep, int(trainer_id))
    _replay_round_sends(pipe, trainer_id, new_eps, stale_plan)


# ---- async clock-only frame coalescing ----------------------------------
# PR 8's fenced delivery ships an EMPTY send_sparse chunk to every
# server each async step purely to carry the per-step seq clock —
# n_servers * n_tables tiny RPCs per step.  The transpiler now stamps
# each async send_sparse op with its clock group (clk_gid) and the
# program's total op count (clk_ops): rowless chunks buffer their
# (table, seq) here instead of shipping, and when the step's LAST
# send_sparse op has run, ONE merged `sparse_clocks` frame per endpoint
# delivers them all.  Monotonic-fence semantics are identical to the
# empty chunks this replaces (nothing journaled, fences advance,
# staleness parks once per frame).
_clk_groups = {}  # clk_gid -> {"n", "seen", "pending": {ep: {table: seq}}}


def _clk_group(attrs):
    gid = attrs.get("clk_gid")
    if gid is None:
        return None
    st = _clk_groups.get(gid)
    if st is None:
        st = _clk_groups[gid] = {"n": int(attrs.get("clk_ops", 1)),
                                 "seen": 0, "pending": {}}
    return st


def _clk_flush(clk, cli_for, tid):
    """End of step: ship the merged clock-only frames, one per endpoint
    that had rowless tables this step.  The incarnation-replay check
    runs FIRST, exactly like the per-step empty chunks this replaces
    did: the clock frame advances the per-table seq fence, and letting
    it move past an un-acked data chunk on a restarted server would
    make the eventual re-send drop as `dup` — a silently lost update,
    the one thing the journal/fence/replay machinery exists to
    prevent."""
    from ..distributed import rpc as _rpc

    pending, clk["pending"] = clk["pending"], {}
    for ep, clocks in sorted(pending.items()):
        cli = cli_for(ep, tid)
        _async_check_replay(cli, ep, tid)
        r = cli.call("sparse_clocks", clocks=clocks, trainer_id=tid)
        _check_not_evicted(r, ep, tid)
        _note_plan(ep, r)
        _rpc.note_async(async_clock_merges=1)


# ---- wire compression (FLAGS_comm_wire_dtype / FLAGS_comm_grad_int8) ---
# int8 error-feedback residuals, TRAINER-side per (endpoint, block):
# each round quantizes (grad + residual) and keeps the quantization
# error for the NEXT round, so the error is corrected over time instead
# of accumulating (the 1-bit/TernGrad error-feedback rule).  The fenced
# replay records store the already-quantized blocks, so a pserver
# restart re-ships identical bytes and the residual stays consistent.
_ef_residuals = {}  # (endpoint, block_name) -> np.ndarray


def reset_fences():
    """Test isolation hook (mirrors rpc.reset_comm_stats)."""
    _fences.clear()
    _ef_residuals.clear()
    _hot_caches.clear()
    _plans.clear()
    _clk_groups.clear()
    from ..distributed import rpc as _rpc

    _rpc.reset_plan_epochs()


def _fence(ep):
    st = _fences.get(ep)
    if st is None:
        st = _fences[ep] = {"inc": None, "step": 0, "fstep": 0,
                            "sends": [], "sparse": {}}
    return st


def _quantize_i8(g):
    """Symmetric per-block int8 quantization: q = round(g / scale) with
    scale = amax/127; returns (q, scale, dequantized)."""
    amax = float(np.max(np.abs(g))) if g.size else 0.0
    scale = amax / 127.0
    if scale == 0.0:
        q = np.zeros(g.shape, np.int8)
        return q, 0.0, np.zeros_like(g)
    q = np.clip(np.rint(g / scale), -127, 127).astype(np.int8)
    return q, scale, (q.astype(g.dtype) * g.dtype.type(scale))


def _compress_block(ep, bname, seg, wire_dtype, grad_int8, raw_out=None):
    """Wrap one dense grad block for the wire per the plan's compression
    metadata; returns the value to ship and notes the saved bytes in the
    comm counters (rpc.get_comm_stats comm_bytes_saved).

    `raw_out` (a dict) receives the PRE-compression f32 block — for int8
    the residual-accumulated value that was actually quantized — so a
    stale-plan recovery can rescale the transition round EXACTLY and
    re-compress, instead of re-shipping wire-compressed bytes at the old
    scale (the PR 10 documented gap, closed here)."""
    from ..distributed import rpc as _rpc

    if seg.dtype.kind != "f":
        return seg
    if grad_int8:
        key = (ep, bname)
        res = _ef_residuals.get(key)
        g = seg + res if res is not None else seg
        q, scale, deq = _quantize_i8(np.ascontiguousarray(g))
        _ef_residuals[key] = g - deq
        if raw_out is not None:
            raw_out[bname] = np.array(g)
        _rpc.note_bytes_saved(seg.nbytes - q.nbytes)
        return _rpc.Int8Wire(q, scale, seg.dtype.str)
    if wire_dtype == "bfloat16":
        # bf16 wire is 2 bytes/element whatever the source float width
        if raw_out is not None:
            raw_out[bname] = np.array(seg)
        _rpc.note_bytes_saved(seg.nbytes - 2 * seg.size)
        return _rpc.Bf16Wire(seg)
    return seg


def _recompress_block(ep, bname, raw, wire_dtype, grad_int8):
    """Re-compress one RESCALED raw block for a stale-plan replay: the
    shipped value is exactly compress(raw) at the current scale, and the
    int8 error-feedback residual is re-derived from this (replacing)
    quantization so the next round's correction stays consistent.
    Idempotent at ratio 1: byte-identical to the original wire value."""
    from ..distributed import rpc as _rpc

    raw = np.asarray(raw)
    if raw.dtype.kind != "f":
        return raw
    if grad_int8:
        q, scale, deq = _quantize_i8(np.ascontiguousarray(raw))
        _ef_residuals[(ep, bname)] = raw - deq
        return _rpc.Int8Wire(q, scale, raw.dtype.str)
    if wire_dtype == "bfloat16":
        return _rpc.Bf16Wire(raw)
    return raw


def _stale_endpoints(eps):
    """Endpoints whose observed incarnation moved past the fence
    baseline.  First observation just seeds the baseline (the register
    handshake at first contact has usually seeded the registry)."""
    from ..distributed import rpc as _rpc

    out = []
    for ep in eps:
        st = _fence(ep)
        cur = _rpc.incarnation_of(ep)
        if st["inc"] is None:
            st["inc"] = cur
        elif cur is not None and cur != st["inc"]:
            out.append(ep)
    return out


def _replay_round_sends(pipe, trainer_id, eps, stale_plan=None):
    """Re-ship the recorded current-round stream to restarted endpoints:
    queued sparse chunks first (they must be pending BEFORE the dense
    fold triggers the round), then the dense buckets.  The submit that
    completes the server's set blocks until the replayed round runs —
    the happens-before edge that makes recovery a fence, not a sleep."""
    import time

    from ..distributed import rpc as _rpc
    from ..distributed.rpc import RPCClient

    t0 = time.perf_counter()
    # the incarnation each replay is ADDRESSED to, captured up front:
    # re-baselining to whatever the drain last observed would mask a
    # SECOND restart landing mid-replay (part of the stream lost again),
    # and the post-fetch staleness check would wrongly see calm
    targets = {ep: _rpc.incarnation_of(ep) for ep in eps}
    for ep in eps:
        st = _fence(ep)
        cli = RPCClient.get(ep)
        for kw in st["sparse"].values():
            r = cli.call("send_sparse", **kw)
            _check_not_evicted(r, ep, trainer_id)
            _note_plan(ep, r)
            if stale_plan is not None and isinstance(r, dict) \
                    and r.get("stale_plan"):
                stale_plan.add(ep)
        for kw in st["sends"]:
            pipe(ep).submit("send_bucket", timeout_s=_BLOCKING_TIMEOUT,
                            **kw)
    for ep in eps:
        _drain_plan_checked(pipe, ep, trainer_id, stale_plan)
        _fence(ep)["inc"] = targets[ep]
    _rpc.note_recovery((time.perf_counter() - t0) * 1e3)


def _check_not_evicted(result, ep, trainer_id):
    """A pserver answers evicted=True to a trainer it declared dead (its
    grads were dropped mid-round).  Training on silently-stale params
    would diverge without a trace — fail fast and loudly instead."""
    if isinstance(result, dict) and result.get("evicted"):
        raise RuntimeError(
            "trainer %s was evicted by pserver %s (missed the liveness "
            "deadline); its sync round moved on without it — restart the "
            "trainer to rejoin" % (trainer_id, ep))


@register("send", side_effect=True)
def _send(ctx, ins, attrs):
    """Split X flat into `sections`, ship block i to epmap[i] as
    block_names[i].  One send op per original grad var."""
    sections = [int(s) for s in attrs["sections"]]
    epmap = list(attrs["epmap"])
    block_names = list(attrs["block_names"])
    trainer_id = int(attrs.get("trainer_id", 0))
    cli = _client_map(trainer_id)
    # the legacy per-variable path ALWAYS ships full precision — tag the
    # counters accordingly even when FLAGS_comm_wire_dtype says bf16
    from ..distributed import rpc as _rpc_mod

    _rpc_mod.note_wire_dtype("float32")

    def host_send(x):
        flat = np.asarray(x).reshape(-1)
        off = 0
        for sec, ep, bname in zip(sections, epmap, block_names):
            r = cli(ep).send_var(bname, flat[off : off + sec], trainer_id)
            _check_not_evicted(r, ep, trainer_id)
            off += sec
        return np.int32(0)

    tok = io_callback(
        host_send, jax.ShapeDtypeStruct((), jnp.int32), ins["X"][0], ordered=True
    )
    return {"Out": [tok]}


@register("send_barrier", side_effect=True)
def _send_barrier(ctx, ins, attrs):
    """Round edge: drain the in-flight send window (bucketed sends are
    submitted async — THIS is where their results, eviction included,
    surface), then barrier every pserver.  The barrier verbs themselves
    ride the window so N pservers round-trip concurrently instead of
    serializing one blocked barrier behind another."""
    endpoints = list(attrs["endpoints"])
    trainer_id = int(attrs.get("trainer_id", 0))
    pipe = _pipelined(trainer_id)

    def host_barrier():
        for ep in endpoints:
            for r in pipe(ep).drain():
                _check_not_evicted(r, ep, trainer_id)
        for ep in endpoints:  # all submitted before any is waited on
            pipe(ep).submit("barrier", timeout_s=_BLOCKING_TIMEOUT,
                            kind="send", trainer_id=trainer_id)
        for ep in endpoints:
            for r in pipe(ep).drain():
                _check_not_evicted(r, ep, trainer_id)
        return np.int32(0)

    tok = io_callback(host_barrier, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": [tok]}


@register("recv", side_effect=True)
def _recv(ctx, ins, attrs):
    """Gather param blocks from epmap, concat + reshape to the param."""
    sections = [int(s) for s in attrs["sections"]]
    epmap = list(attrs["epmap"])
    block_names = list(attrs["block_names"])
    shape = [int(s) for s in attrs["shape"]]
    dtype = jdt(attrs.get("dtype", "float32"))
    trainer_id = int(attrs.get("trainer_id", 0))
    cli = _client_map(trainer_id)

    def host_recv():
        parts = [
            np.asarray(cli(ep).get_var(bname, trainer_id)).reshape(-1)
            for ep, bname in zip(epmap, block_names)
        ]
        out = np.concatenate(parts).reshape(shape)
        return out.astype(
            np.dtype(dtype.name if hasattr(dtype, "name") else dtype),
            copy=False)

    out = io_callback(
        host_recv, jax.ShapeDtypeStruct(tuple(shape), dtype), ordered=True
    )
    return {"Out": [out]}


@register("fetch_barrier", side_effect=True)
def _fetch_barrier(ctx, ins, attrs):
    endpoints = list(attrs["endpoints"])
    trainer_id = int(attrs.get("trainer_id", 0))
    pipe = _pipelined(trainer_id)

    def host_barrier():
        for ep in endpoints:  # concurrent across pservers (see send_barrier)
            pipe(ep).submit("barrier", timeout_s=_BLOCKING_TIMEOUT,
                            kind="fetch", trainer_id=trainer_id)
        for ep in endpoints:
            pipe(ep).drain()
        return np.int32(0)

    tok = io_callback(host_barrier, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": [tok]}


@register("send_bucket", side_effect=True)
def _send_bucket(ctx, ins, attrs):
    """Coalesced, pipelined grad push.  The transpiler's bucket plan maps
    flat slices of the input grads into size-capped per-endpoint buckets
    (attrs['buckets'] = [[endpoint, [[x_idx, begin, end, block_name],
    ...]], ...]); each bucket ships as ONE send_bucket frame through the
    windowed PipelinedClient, so bucket N+1 serializes while bucket N is
    on the wire.  Results (including eviction notices) surface at the
    window drain: send_barrier in sync mode, the next recv_bucket in
    async."""
    plan = [(ep, [(int(xi), int(b), int(e), bn) for xi, b, e, bn in entries])
            for ep, entries in attrs["buckets"]]
    trainer_id = int(attrs.get("trainer_id", 0))
    # async fenced delivery: each async bucket carries a per-endpoint
    # aseq token; the server journals the applied bucket and dedupes an
    # RPC-retry re-delivery straddling a restart (exactly-once)
    async_fence = bool(attrs.get("async_fence"))
    # sync mode: per-endpoint bucket counts — the server folds the send
    # barrier into the arrival of the LAST bucket (ps_server), so that
    # submit may block round-long and gets the blocking timeout
    totals = {ep: int(n) for ep, n in (attrs.get("sync_totals") or {}).items()}
    # wire-compression metadata from the transpiler's bucket plan: both
    # ends agree because the requester's plan declares the wire form
    wire_dtype = str(attrs.get("wire_dtype") or "float32")
    grad_int8 = bool(attrs.get("grad_int8"))
    compressing = grad_int8 or wire_dtype != "float32"
    # the COUNTERS tag must describe the PLANNED wire, which may differ
    # from the global flag (DistributeTranspilerConfig override)
    from ..distributed import rpc as _rpc_mod

    _rpc_mod.note_wire_dtype(wire_dtype)
    # elastic autoscaling: the declarative plan spec (when stamped)
    # makes this op's bucket layout + grad scale re-derivable at
    # runtime; plan_rt is the program's shared runtime plan state
    plan_rt = _plan_rt(attrs)
    plan_eps = sorted({ep for ep, _ in plan})
    pipe = _pipelined(trainer_id)

    def host_send(*grads):
        from ..profiler import RecordEvent

        use_plan, use_totals, corr, pepoch = plan, totals, 1.0, None
        if plan_rt is not None:
            _maybe_replan(plan_rt, _plan_eps_now(plan_rt, plan_eps),
                          trainer_id)
            corr = plan_rt["corr"]
            pepoch = plan_rt["epoch"]
            if plan_rt["derived"] is not None:
                # the re-derived plan REPLACES the transpile-time one —
                # for an unchanged world it is bit-identical (the
                # derive_plan contract), so this swap is exercised on
                # every re-plan, not just on layout changes
                d = plan_rt["derived"]
                use_plan = [
                    (ep, [(int(xi), int(b), int(e), bn)
                          for xi, b, e, bn in entries])
                    for ep, entries in d["send_buckets"]]
                use_totals = (d["sync_totals"] if totals else {})
        flats = [_scale_corr(np.asarray(g).reshape(-1), corr)
                 for g in grads]
        per_ep = {}
        raw_by_ep = {}  # pre-compression blocks (exact plan-replay)
        with RecordEvent("wire_compress", cat="compress") \
                if compressing else _null_ctx():
            for ep, entries in use_plan:
                raw_out = raw_by_ep.setdefault(ep, {})
                blocks = {
                    bn: _compress_block(ep, bn, flats[xi][b:e],
                                        wire_dtype, grad_int8,
                                        raw_out=raw_out)
                    if compressing else flats[xi][b:e]
                    for xi, b, e, bn in entries}
                per_ep.setdefault(ep, []).append(blocks)
        # uniform step token for the round: per-endpoint counters advance
        # in lockstep, and an endpoint JOINING mid-job (live pserver
        # migration) must adopt the round's token — starting it at 1
        # would collide with the fold fences that migrated with its
        # adopted shards (its first real rounds would drop as replays)
        new_step = 1 + max((_fence(ep)["step"] for ep in per_ep),
                           default=0)
        for ep, blist in per_ep.items():
            total = use_totals.get(ep)
            if not total:
                if async_fence:
                    st = _async_st(ep)
                    for blocks in blist:
                        st["aseq"] += 1
                        if len(st["udense"]) >= _ASYNC_RESEND_MAX:
                            raise RuntimeError(
                                "async dense resend queue for %s "
                                "overflowed (%d un-acked buckets): the "
                                "pserver has not acked in %d buckets — "
                                "failing loudly instead of dropping "
                                "durability" % (ep, len(st["udense"]),
                                                _ASYNC_RESEND_MAX))
                        # recorded BEFORE the submit: a plan flip that
                        # drops this bucket (stale shard) re-ships it
                        # from here to the new owner
                        st["udense"][st["aseq"]] = blocks
                        pipe(ep).submit(
                            "send_bucket", blocks=blocks,
                            trainer_id=trainer_id, seq_total=None,
                            aseq=st["aseq"])
                else:
                    for blocks in blist:  # async legacy: unfenced
                        pipe(ep).submit("send_bucket", blocks=blocks,
                                        trainer_id=trainer_id,
                                        seq_total=None)
                continue
            # sync: mint this round's step token, record the stream for
            # incarnation-fenced replay, stamp each bucket's seq_idx so
            # the server counts arrivals by SET (replay-idempotent)
            st = _fence(ep)
            if st["inc"] is None:
                # baseline = the incarnation the register handshake saw:
                # a restart during even the FIRST round must be fenced
                from ..distributed import rpc as _rpc

                st["inc"] = _rpc.incarnation_of(ep)
            st["step"] = new_step
            # the corr the recorded blocks were scaled with: a stale-
            # plan replay rescales them to the then-current corr — and
            # the PRE-compression raw blocks ride alongside, so that
            # rescale is EXACT under a compressed wire (re-compress
            # after rescale, never rescaled-compressed bytes)
            st["corr"] = corr
            st["raw"] = raw_by_ep.get(ep, {})
            # declare this step's sparse manifest on every dense bucket:
            # the server must not fold (and run the round) until each
            # declared chunk is pending.  Without this, a crash after
            # the sparse acks lets RPC-level retries of the UNACKED
            # dense buckets assemble the round on the restarted server
            # with the sparse rows lost in the dead incarnation's
            # memory — and the fold fence would then drop the fenced
            # replay's corrective chunks as dup_round.
            declared = (sorted(st["sparse"])
                        if st.get("sparse_step") == st["step"] else [])
            st["sends"] = [
                dict(blocks=blocks, trainer_id=trainer_id, seq_total=total,
                     step=st["step"], seq_idx=i, sparse_tables=declared)
                for i, blocks in enumerate(blist)]
            if pepoch is not None:
                for kw in st["sends"]:
                    kw["pepoch"] = pepoch
            for kw in st["sends"]:
                pipe(ep).submit("send_bucket", timeout_s=_BLOCKING_TIMEOUT,
                                **kw)
        if plan_rt is not None:
            # this round's records were made under the CURRENT derived
            # layout: a later fence replays them in place (a further
            # endpoint-set change re-arms the flag via _maybe_replan)
            plan_rt["relayout"] = False
        return np.int32(0)

    tok = io_callback(
        host_send, jax.ShapeDtypeStruct((), jnp.int32), *ins["X"],
        ordered=True)
    return {"Out": [tok]}


@register("recv_bucket", side_effect=True)
def _recv_bucket(ctx, ins, attrs):
    """Coalesced, pipelined param pull: one get_bucket frame per
    (endpoint, bucket) — submitted for every pserver BEFORE any reply is
    awaited, so N pservers serve concurrently — then each param is
    reassembled host-side from its block slices.  Drains the send window
    first: in async mode (no send_barrier) the gets must not overtake
    this step's own grads."""
    buckets = [(ep, [str(n) for n in names]) for ep, names in
               attrs["buckets"]]
    params = [(p, [int(d) for d in shape], str(dtype), list(bnames))
              for p, shape, dtype, bnames in attrs["params"]]
    trainer_id = int(attrs.get("trainer_id", 0))
    # sync mode: the server folds the fetch barrier into the last served
    # bucket per endpoint (see ps_server._h_get_bucket)
    totals = {ep: int(n) for ep, n in (attrs.get("fetch_totals") or {}).items()}
    # param-side wire compression: the request DECLARES the wire dtype
    # (from the transpiler plan) and the server compresses its reply;
    # the decoder hands back the original dtype transparently
    wire_dtype = str(attrs.get("wire_dtype") or "float32")
    plan_rt = _plan_rt(attrs)
    pipe = _pipelined(trainer_id)
    out_structs = [
        jax.ShapeDtypeStruct(tuple(shape), jdt(dtype))
        for _, shape, dtype, _ in params
    ]

    def host_recv():
        def layout():
            """The CURRENT fetch layout: the derived plan's when one
            exists (live pserver migration moves buckets between
            endpoints mid-job), else the transpile-time attrs.  Block
            names and param reassembly are layout-invariant (stable
            shards) — only the grouping moves."""
            if plan_rt is not None and plan_rt.get("derived") is not None:
                d = plan_rt["derived"]
                lb = [(str(ep), [str(n) for n in names])
                      for ep, names in d["recv_buckets"]]
                lt = ({str(ep): int(n)
                       for ep, n in (d.get("fetch_totals") or {}).items()}
                      if totals else {})
                return lb, lt
            return buckets, totals

        cur_buckets, cur_totals = layout()
        eps_here = sorted({ep for ep, _ in cur_buckets})
        # endpoints whose servers FENCED this round's frames as stale-
        # plan (our world was out of date): re-plan, then re-ship — the
        # elastic sibling of the incarnation replay below
        stale_plan = set()
        for ep in eps_here:
            _drain_plan_checked(pipe, ep, trainer_id, stale_plan)
        fenced = bool(totals)
        minted = set()
        round_fstep = [None]

        def mint(eps_list):
            # ONE fetch step token per logical step, shared across the
            # endpoints (their counters advance in lockstep); replays
            # inside this invocation reuse it (the server dedups by set
            # / fold fence).  A replan can add NEW endpoints
            # mid-recovery — they adopt the round's token on first
            # appearance, aligned with the fetch fences that migrated
            # with their adopted shards.
            fresh = [ep for ep in eps_list if ep not in minted]
            if not fresh:
                return
            if round_fstep[0] is None:
                round_fstep[0] = 1 + max(
                    (_fence(ep)["fstep"] for ep in fresh), default=0)
            for ep in fresh:
                minted.add(ep)
                _fence(ep)["fstep"] = round_fstep[0]

        if fenced:
            mint(eps_here)
        elif stale_plan and plan_rt is not None:
            # async: a drained send reply was fenced (stale shard after
            # a migration flip) — re-plan NOW so the next step routes to
            # the new owners, then re-ship the DROPPED dense buckets
            # from the udense resend queue under the new dispatch
            # (formerly skipped — only sparse survived a flip).
            targets = sorted(stale_plan)
            _maybe_replan(plan_rt, eps_here, trainer_id)
            _async_replay_dense(pipe, plan_rt, trainer_id, targets)
            stale_plan.clear()
        block_vals = {}
        to_fetch = list(eps_here)
        for _attempt in range(_MAX_ROUND_REPLAYS):
            for _replan_try in range(_MAX_ROUND_REPLAYS):
                if not (fenced and plan_rt is not None and stale_plan):
                    break
                # plan-epoch fence tripped: refresh the plan from the
                # server's current world, restamp + rescale (exactly —
                # re-compressed from recorded raws) the recorded round
                # stream and re-ship it BEFORE any fetch — the dropped
                # frames mean the round never assembled there, so
                # fetching first would park on params that are never
                # coming.  The replay's own drains feed `stale_plan`
                # back, so a SECOND mint landing mid-recovery loops
                # (bounded) instead of being swallowed.
                _maybe_replan(plan_rt, eps_here, trainer_id)
                targets = sorted(stale_plan)
                stale_plan.clear()
                _replay_round_plan(pipe, trainer_id, targets, plan_rt,
                                   stale_plan)
                cur_buckets, cur_totals = layout()
                eps_here = sorted({ep for ep, _ in cur_buckets})
                to_fetch = list(eps_here)
            if fenced and plan_rt is not None and stale_plan:
                # still fenced after the last allowed replay (a for/else
                # would also fire when the FINAL replay just succeeded)
                raise RuntimeError(
                    "sync round could not complete: plan epochs moved "
                    "faster than %d re-plan replays (membership is "
                    "flapping beyond the policy's damping)"
                    % _MAX_ROUND_REPLAYS)
            if fenced:
                # a bump between this round's sends and here means the
                # server restarted from its round-boundary checkpoint:
                # re-ship the round's stream before pulling params
                stale = _stale_endpoints(eps_here)
                if stale:
                    _replay_round_sends(pipe, trainer_id, stale,
                                        stale_plan)
            per_ep_names = {}
            for ep, names in cur_buckets:
                per_ep_names.setdefault(ep, []).append(names)
            if fenced:
                mint(to_fetch)
            futs = []
            for ep in to_fetch:
                for i, names in enumerate(per_ep_names.get(ep, [])):
                    kw = dict(names=names, trainer_id=trainer_id,
                              fetch_total=cur_totals.get(ep),
                              step=_fence(ep)["fstep"] if fenced else None,
                              seq_idx=i)
                    if wire_dtype != "float32":
                        kw["wire_dtype"] = wire_dtype
                    futs.append((ep, pipe(ep).submit(
                        "get_bucket", timeout_s=_BLOCKING_TIMEOUT, **kw)))
            for ep, f in futs:
                got = f.result()
                if not isinstance(got, dict):
                    raise RuntimeError(
                        "get_bucket from %s returned %r" % (ep, type(got)))
                if got.get("stale_plan") is True and "pepoch" in got:
                    # the fetch named a migrated-away block: this
                    # endpoint's layout moved under us — re-plan and
                    # re-pull under the new dispatch (the replay loop
                    # above re-ships the round first)
                    _note_plan(ep, got)
                    stale_plan.add(ep)
                    continue
                if wire_dtype == "bfloat16":
                    from ..distributed import rpc as _rpc

                    # bf16 wire = 2 bytes/element regardless of the
                    # block's float width (f64 saves 3/4, not 1/2)
                    _rpc.note_bytes_saved(sum(
                        v.nbytes - 2 * v.size for v in got.values()
                        if getattr(v, "dtype", None) is not None
                        and v.dtype.kind == "f"))
                block_vals.update(got)
            for ep in to_fetch:
                # clear resolved futures off the window
                _drain_plan_checked(pipe, ep, trainer_id, stale_plan)
            if not fenced:
                if stale_plan and plan_rt is not None:
                    # async: a fetch named a migrated-away block (or a
                    # send was fenced) — re-plan and re-pull the whole
                    # layout; breaking here would leave the moved
                    # blocks missing from block_vals and crash the
                    # reassembly below
                    _maybe_replan(plan_rt, eps_here, trainer_id)
                    stale_plan.clear()
                    cur_buckets, cur_totals = layout()
                    eps_here = sorted({ep for ep, _ in cur_buckets})
                    to_fetch = list(eps_here)
                    continue
                break
            # a restart DURING the fetch served params from a snapshot
            # that may predate this round: replay + re-pull — but ONLY
            # from the stale endpoints.  A healthy peer whose fetch
            # barrier already drained has params_ready off, and a
            # redundant re-pull there would park on a flag only the
            # NEXT round sets
            stale = _stale_endpoints(eps_here)
            if not stale and not stale_plan:
                break
            to_fetch = stale or sorted(stale_plan)
        else:
            raise RuntimeError(
                "sync round could not complete: pserver(s) restarted "
                "faster than %d fenced replays" % _MAX_ROUND_REPLAYS)
        outs = []
        for p, shape, dtype, bnames in params:
            flat = np.concatenate(
                [np.asarray(block_vals[bn]).reshape(-1) for bn in bnames])
            dt = jdt(dtype)
            outs.append(flat.reshape(shape).astype(
                np.dtype(dt.name if hasattr(dt, "name") else dt),
                copy=False))
        return tuple(outs)

    outs = io_callback(host_recv, tuple(out_structs), ordered=True)
    return {"Out": list(outs)}


@register("prefetch", no_grad_inputs={"Ids", "Dep"}, side_effect=True)
def _prefetch(ctx, ins, attrs):
    """Distributed embedding lookup (prefetch_op / split_ids / merge_ids
    analog): route each id to server id%nservers, fetch rows, merge back
    in input order.  Fixed id-array shape keeps XLA happy; routing is
    host-side.

    Collective (hybrid) mode: the op runs once per mesh REPLICA with
    that replica's id shard; the logical trainer id is the replica's
    axis_index (a runtime value fed into the callback).  The optional
    ``Dep`` input — an allreduce-updated param the transpiler wires in —
    orders this lookup after the PREVIOUS step's update, so every
    replica's step-N sparse push has landed before any step-N+1 read."""
    ids = ins["Ids"][0]
    epmap = list(attrs["epmap"])
    table_names = list(attrs["table_names"])
    emb_dim = int(attrs["emb_dim"])
    trainer_id = int(attrs.get("trainer_id", 0))
    collective = bool(attrs.get("collective"))
    # async fenced mode (transpiler-stamped): lookups carry this
    # trainer's logical clock so the server can PARK a reader running
    # past FLAGS_async_staleness_bound, and a hot-row cache
    # (FLAGS_sparse_hot_rows) serves repeat ids without the RPC
    async_fence = bool(attrs.get("async_fence"))
    hot_opt = attrs.get("hot_opt")
    n = len(epmap)

    id_shape = tuple(ids.shape)
    out_shape = id_shape + (emb_dim,)

    if collective:
        cli_for = _rank_clients(epmap)
    else:
        _cli = _client_map(trainer_id)

        def cli_for(ep, _tid):
            return _cli(ep)

    # live pserver migration: lookups consult the shared runtime plan so
    # a moved shard is read from its NEW owner (a stale read answers a
    # stale_plan dict — re-plan and retry once at the fresh route)
    plan_rt = _plan_rt(attrs) if not collective else None

    def host_prefetch(tid, ids_v):
        """ONE routing core for both trainer-id sources: ids route to
        their stable shard (id % n_base), whose endpoint the current
        plan names; rows merge back in input order."""
        flat = np.asarray(ids_v).reshape(-1).astype(np.int64)
        out = np.zeros((flat.size, emb_dim), dtype=np.float32)
        cache = (_hot_cache_for(table_names, hot_opt)
                 if async_fence and not collective else None)
        want = np.ones(flat.size, bool)
        if cache is not None:
            cache.tick()
            hits, want = cache.lookup(flat)
            for i, g in enumerate(flat):
                if not want[i]:
                    out[i] = hits[int(g)]
        clock = None
        if plan_rt is not None:
            _maybe_replan(plan_rt, _plan_eps_now(plan_rt, epmap), tid)
        for s in range(n):
            ep = _sparse_route(plan_rt, s, epmap)
            if async_fence and not collective:
                cli = cli_for(ep, tid)
                _async_check_replay(cli, ep, tid)
                st = _async_st(ep)
                clock = max(st["sseq"].values()) if st["sseq"] else None
            mask = want & ((flat % n) == s)
            if not mask.any():
                continue
            kw = dict(table=table_names[s], ids=flat[mask] // n,
                      trainer_id=tid)
            if clock is not None:
                kw["clock"] = clock
            rows = cli_for(ep, tid).call("prefetch", **kw)
            if isinstance(rows, dict):
                # migrated-away shard: re-plan, retry at the new owner
                _note_plan(ep, rows)
                if rows.get("stale_plan") and plan_rt is not None:
                    _maybe_replan(plan_rt,
                                  _plan_eps_now(plan_rt, epmap), tid)
                    ep = _sparse_route(plan_rt, s, epmap)
                    rows = cli_for(ep, tid).call("prefetch", **kw)
                if isinstance(rows, dict):
                    raise RuntimeError(
                        "prefetch of %s from %s failed: %r"
                        % (table_names[s], ep, rows))
            rows = np.asarray(rows)
            out[mask] = rows
            if cache is not None:
                cache.insert(flat[mask], rows)
        return out.reshape(out_shape)

    struct = jax.ShapeDtypeStruct(out_shape, jnp.float32)
    if collective:
        rank = _replica_rank(trainer_id)
        deps = [v for v in ins.get("Dep", []) if v is not None]
        if deps:
            # ordering edge only: tie the (scalar) rank operand to the
            # allreduce-updated param via an optimization barrier instead
            # of shipping the whole param to the host as a dead callback
            # operand — same happens-before, zero extra host traffic
            from .collective_ops import _tie

            rank = _tie(rank, deps)
        out = io_callback(
            lambda rank_v, ids_v: host_prefetch(
                int(np.asarray(rank_v)), ids_v),
            struct, rank, ids, ordered=True)
    else:
        out = io_callback(
            lambda ids_v: host_prefetch(trainer_id, ids_v),
            struct, ids, ordered=True)
    return {"Out": [out]}


@register("send_sparse", no_grad_inputs={"Ids"}, side_effect=True)
def _send_sparse(ctx, ins, attrs):
    """Push sparse embedding grads (SelectedRows semantics): rows keyed by
    Ids go back to their owning server — applied at the round barrier in
    sync mode, immediately in async (see ps_server._h_send_sparse).

    ``wire_dtype='bfloat16'`` (stamped from the transpiler plan) ships
    the row VALUES bf16-compressed under the versioned `h` array tag —
    ids and row counts stay exact, the payload halves, and the codec
    hands the server back the original dtype.  The fenced-replay record
    keeps the already-wrapped rows, so a pserver restart re-ships
    byte-identical chunks.

    Collective (hybrid) mode: one push per mesh replica, logical trainer
    id = the replica's axis_index (runtime value), applied per-arrival
    server-side (the transpiler plans sync_mode=False — there is no
    dense round barrier in the collective backend)."""
    ids, grad = ins["Ids"][0], ins["Grad"][0]
    epmap = list(attrs["epmap"])
    table_names = list(attrs["table_names"])
    trainer_id = int(attrs.get("trainer_id", 0))
    scale = float(attrs.get("scale", 1.0))
    sync_mode = bool(attrs.get("sync_mode", False))
    collective = bool(attrs.get("collective"))
    # async fenced delivery (transpiler-stamped): chunks carry per-table
    # seq tokens, ship to EVERY server each step (empty chunks included,
    # so the seq is a uniform logical clock — rowless routing must not
    # make a healthy trainer look stalled to some shard), and un-acked
    # chunks re-ship on an incarnation bump
    async_fence = bool(attrs.get("async_fence"))
    hot_opt = attrs.get("hot_opt")
    wire_dtype = str(attrs.get("wire_dtype") or "float32")
    n = len(epmap)

    def _wrap_rows(rows):
        """Row values onto the planned wire: bf16 halves float payloads
        (the PR 5 f32-only gap for sparse chunks); ids stay exact."""
        if wire_dtype != "bfloat16" or rows.dtype.kind != "f" \
                or not rows.size:
            return rows
        from ..distributed import rpc as _rpc

        _rpc.note_bytes_saved(rows.nbytes - 2 * rows.size)
        return _rpc.Bf16Wire(rows)

    if collective:
        cli_for = _rank_clients(epmap)
    else:
        _cli = _client_map(trainer_id)

        def cli_for(ep, _tid):
            return _cli(ep)

    # elastic autoscaling: shares the program's runtime plan state with
    # the bucket ops (the transpiler stamps the same plan_gid), so the
    # sparse scale correction and plan epoch move in lockstep with dense
    plan_rt = _plan_rt(attrs) if not collective else None

    def host_push(tid, ids_v, grad_v):
        """ONE routing core for both trainer-id sources: rows route to
        server id%n.  sync_mode (never set on the collective plan — no
        dense round exists there) additionally stamps step tokens and
        records the chunk for incarnation-fenced replay."""
        corr, pepoch = 1.0, None
        if plan_rt is not None:
            _maybe_replan(plan_rt, _plan_eps_now(plan_rt, epmap), tid)
            corr, pepoch = plan_rt["corr"], plan_rt["epoch"]
        flat = np.asarray(ids_v).reshape(-1).astype(np.int64)
        g = np.asarray(grad_v).reshape(flat.size, -1) * scale
        # elastic scale correction: the transpile-time 1/N0 becomes
        # 1/N_live (corr == 1.0 for an unchanged world — bit-identical)
        g = _scale_corr(g, corr)
        if async_fence and not collective:
            cache = _hot_cache_for(table_names, hot_opt)
            if cache is not None:
                # mirror the push on the cached copies BEFORE shipping:
                # the next (cache-hit) lookup sees this step's update
                cache.push(flat, g)
        for s in range(n):
            mask = (flat % n) == s
            # live pserver migration: shard s ships to its CURRENT owner
            ep = _sparse_route(plan_rt, s, epmap)
            if plan_rt is not None:
                routes = plan_rt.setdefault("sparse_routes", {})
                prev_ep = routes.get(table_names[s])
                if prev_ep is not None and prev_ep != ep:
                    _move_async_sparse_state(prev_ep, ep, table_names[s])
                routes[table_names[s]] = ep
            if async_fence and not collective:
                from ..distributed import rpc as _rpc

                st = _async_st(ep)
                table = table_names[s]
                seq = st["sseq"].get(table, 0) + 1
                st["sseq"][table] = seq
                clk = _clk_group(attrs)
                if clk is not None and not mask.any():
                    # clock-only chunk: nothing to apply — buffer the
                    # (table, seq) clock and let the step's ONE merged
                    # sparse_clocks frame per endpoint deliver it
                    # (previously each rowless table shipped its own
                    # empty send_sparse: n_servers * n_tables tiny RPCs
                    # per async step).  Not queued for resend — the
                    # fence is monotonic, a lost clock is superseded by
                    # the next step's.
                    clk["pending"].setdefault(ep, {})[table] = seq
                    continue
                cli = cli_for(ep, tid)
                _async_check_replay(cli, ep, tid)
                kw = dict(table=table, ids=flat[mask] // n,
                          rows=_wrap_rows(g[mask]), trainer_id=tid,
                          seq=seq)
                uq = st["unacked"].setdefault(table, {})
                if len(uq) >= _ASYNC_RESEND_MAX:
                    raise RuntimeError(
                        "async resend queue for %s@%s overflowed (%d "
                        "un-acked chunks): the pserver has not acked in "
                        "%d steps — failing loudly instead of dropping "
                        "durability" % (table, ep, len(uq),
                                        _ASYNC_RESEND_MAX))
                uq[seq] = kw
                r = cli.call("send_sparse", **kw)
                _check_not_evicted(r, ep, tid)
                _note_plan(ep, r)
                if plan_rt is not None and isinstance(r, dict) \
                        and r.get("stale_plan"):
                    # migrated-away shard (async): re-plan, carry the
                    # fence state to the new owner, re-ship there — the
                    # chunk is still in the (moved) resend queue, so a
                    # crash here re-delivers and the owner's migrated
                    # (trainer, table) fence dedupes
                    _maybe_replan(plan_rt,
                                  _plan_eps_now(plan_rt, epmap), tid)
                    new_ep = _sparse_route(plan_rt, s, epmap)
                    if new_ep != ep:
                        _move_async_sparse_state(ep, new_ep, table)
                        plan_rt.setdefault("sparse_routes",
                                           {})[table] = new_ep
                        ep = new_ep
                        st = _async_st(ep)
                        cli = cli_for(ep, tid)
                    r = cli.call("send_sparse", **kw)
                    _check_not_evicted(r, ep, tid)
                    _note_plan(ep, r)
                _async_note_ack(st, table, r)
                _rpc.note_async(async_sparse_sends=1)
                continue
            if not mask.any():
                continue
            kw = dict(table=table_names[s], ids=flat[mask] // n,
                      rows=_wrap_rows(g[mask]), trainer_id=tid)
            if sync_mode:
                # stamp the chunk with the UPCOMING dense step token
                # (this training step's send_bucket mints step+1) and
                # record it for incarnation-fenced replay — the server's
                # keyed pending slot + fold fence keep replays idempotent.
                # Keyed by TABLE so the record stays bounded even on the
                # legacy per-var path, where no send_bucket advances the
                # step token and the reset-on-new-step never fires
                st = _fence(ep)
                # the UPCOMING round's token, computed like send_bucket's
                # uniform mint (1 + max across the plan's endpoints): a
                # per-endpoint `st["step"] + 1` would stamp a chunk to a
                # freshly-routed owner with step=1 against fold fences
                # that migrated at round N — silently dropped as
                # dup_round and missing from the round's declared
                # manifest (one round's sparse grads lost)
                step = 1 + max(
                    (_fence(e)["step"]
                     for e in _plan_eps_now(plan_rt, epmap)),
                    default=st["step"])
                kw["step"] = step
                if pepoch is not None:
                    # the plan-epoch fence covers sparse chunks too: a
                    # stale-world chunk must not queue into a current-
                    # epoch round (recv_bucket's recovery re-ships it)
                    kw["pepoch"] = pepoch
                if st.get("sparse_step") != step:
                    st["sparse_step"] = step
                    st["sparse"] = {}
                    st["sparse_raw"] = {}
                    st["sparse_idx"] = {}
                st["sparse"][table_names[s]] = kw
                # the UNWRAPPED rows + the shard's stable index ride the
                # record, so a stale-plan recovery rescales EXACTLY
                # (re-wrap after rescale) and can re-route the chunk to
                # a migrated shard's new owner
                st.setdefault("sparse_raw", {})[table_names[s]] = \
                    np.array(g[mask])
                st.setdefault("sparse_idx", {})[table_names[s]] = s
            r = cli_for(ep, tid).call("send_sparse", **kw)
            _check_not_evicted(r, ep, tid)
            _note_plan(ep, r)
            if plan_rt is not None and isinstance(r, dict) \
                    and r.get("stale_plan"):
                # fenced at an old epoch (the mint landed between this
                # step's re-plan check and now): re-plan IMMEDIATELY
                # and re-ship this chunk at the current epoch — the
                # step's dense buckets are about to declare it in their
                # sparse manifest, and a dropped chunk would leave the
                # fold refusing (need_sparse) forever.  A second mint
                # racing the retry is caught by the dense path: its
                # buckets (same refreshed epoch) get fenced too, and
                # recv_bucket's recovery re-ships the recorded chunk.
                old_corr = plan_rt["corr"]
                _maybe_replan(plan_rt, _plan_eps_now(plan_rt, epmap),
                              tid)
                kw["pepoch"] = plan_rt["epoch"]
                st = _fence(ep)
                raw = (st.get("sparse_raw") or {}).get(table_names[s])
                if raw is not None and old_corr:
                    # EXACT rescale: re-wrap the recorded raw rows at
                    # the fresh corr (never rescale compressed bytes)
                    raw = _scale_corr(np.asarray(raw),
                                      plan_rt["corr"] / old_corr)
                    st["sparse_raw"][table_names[s]] = raw
                    kw["rows"] = _wrap_rows_wire(raw, wire_dtype)
                elif isinstance(kw.get("rows"), np.ndarray) and old_corr:
                    kw["rows"] = _scale_corr(
                        kw["rows"], plan_rt["corr"] / old_corr)
                # the shard may have MOVED (live pserver migration):
                # re-route the chunk — and its fence record — to the
                # current owner
                new_ep = _sparse_route(plan_rt, s, epmap)
                if new_ep != ep:
                    st["sparse"].pop(table_names[s], None)
                    (st.get("sparse_raw") or {}).pop(table_names[s],
                                                     None)
                    (st.get("sparse_idx") or {}).pop(table_names[s],
                                                     None)
                    nst = _fence(new_ep)
                    if nst.get("sparse_step") != kw["step"]:
                        nst["sparse_step"] = kw["step"]
                        nst["sparse"] = {}
                        nst["sparse_raw"] = {}
                        nst["sparse_idx"] = {}
                    nst["sparse"][table_names[s]] = kw
                    if raw is not None:
                        nst.setdefault("sparse_raw",
                                       {})[table_names[s]] = raw
                    nst.setdefault("sparse_idx",
                                   {})[table_names[s]] = s
                    ep = new_ep
                r = cli_for(ep, tid).call("send_sparse", **kw)
                _check_not_evicted(r, ep, tid)
                _note_plan(ep, r)
        if async_fence and not collective:
            clk = _clk_group(attrs)
            if clk is not None:
                clk["seen"] += 1
                if clk["seen"] >= clk["n"]:
                    # the step's LAST async sparse op ran: flush the
                    # merged clock-only frames (one per endpoint)
                    clk["seen"] = 0
                    _clk_flush(clk, cli_for, tid)
        return np.int32(0)

    struct = jax.ShapeDtypeStruct((), jnp.int32)
    if collective:
        tok = io_callback(
            lambda rank_v, ids_v, grad_v: host_push(
                int(np.asarray(rank_v)), ids_v, grad_v),
            struct, _replica_rank(trainer_id), ids, grad, ordered=True)
    else:
        tok = io_callback(
            lambda ids_v, grad_v: host_push(trainer_id, ids_v, grad_v),
            struct, ids, grad, ordered=True)
    return {"Out": [tok]}


@register("checkpoint_notify", side_effect=True)
def _checkpoint_notify(ctx, ins, attrs):
    """distributed_ops/checkpoint_notify_op.cc: in-program trigger asking
    every pserver in `epmap` to snapshot its shard into `dir` (host
    callback, ordered with the surrounding sends/barriers)."""
    epmap = list(attrs.get("epmap", []))
    ckpt_dir = attrs.get("dir") or None
    trainer_id = int(attrs.get("trainer_id", 0))
    cli = _client_map(trainer_id)

    def host_notify():
        for ep in epmap:
            cli(ep).checkpoint_notify(dir=ckpt_dir, trainer_id=trainer_id)
        return np.int32(0)

    tok = io_callback(
        host_notify, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {"Out": [tok]}


@register("ref_by_trainer_id", no_grad_inputs=("TrainerId",))
def _ref_by_trainer_id(ctx, ins, attrs):
    """distributed_ops/ref_by_trainer_id_op.h: select X[trainer_id] from
    the input list.  The trainer id is a host-known scalar in every real
    program (wired by the transpiler from the env contract), so the
    selection happens at trace time when possible; a traced id falls back
    to lax.switch over the (equal-shaped) candidates."""
    import jax.core

    xs = ins["X"]
    tid = ins["TrainerId"][0]
    if not isinstance(tid, jax.core.Tracer):
        idx = int(np.asarray(tid).reshape(-1)[0])
        if idx < 0 or idx >= len(xs):
            raise IndexError(
                "ref_by_trainer_id: trainer id %d out of range (%d inputs)"
                % (idx, len(xs)))
        return {"Out": [xs[idx]]}
    import jax.lax as lax

    return {"Out": [lax.switch(
        jnp.clip(tid.reshape(()).astype(jnp.int32), 0, len(xs) - 1),
        [lambda i=i: xs[i] for i in range(len(xs))])]}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py): the rpc ops are side-effecting
# wire endpoints — schema-only registrations (outputs are tokens or
# service-delivered params the transpiler declares)
# ---------------------------------------------------------------------------
from ..analysis.infer import register_infer  # noqa: E402

register_infer("send_bucket", req_ins=(), req_outs=())(None)
register_infer("recv_bucket", req_ins=(), req_outs=())(None)
register_infer("send_sparse", req_ins=("Ids",), req_outs=())(None)
register_infer("prefetch", req_ins=("Ids",), req_outs=("Out",))(None)
