"""Long-tail op lowerings closing the remaining REGISTER_OPERATOR gaps
(SURVEY §2.6): misc math, sequence utilities in the padded representation,
rnn units, metric ops, and op-level save/load (host callbacks)."""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from .common import jdt, stable_compact


# ---------------------------------------------------------------------------
# misc math (minus_op.cc, l1_norm_op.cc, fill_op.cc, hash_op.cc)
# ---------------------------------------------------------------------------
@register("minus")
def _minus(ctx, ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register("l1_norm")
def _l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0])).reshape(1)]}


@register("fill")
def _fill(ctx, ins, attrs):
    """fill_op.cc: write a literal value list into a tensor."""
    shape = [int(s) for s in attrs["shape"]]
    dtype = jdt(attrs.get("dtype", "float32"))
    value = np.asarray(attrs["value"], dtype=np.float64).reshape(shape)
    return {"Out": [jnp.asarray(value).astype(dtype)]}


@register("hash", no_grad_inputs=("X",))
def _hash(ctx, ins, attrs):
    """hash_op.cc: bucketed integer hashing for sparse id spaces — the
    xxhash of the reference becomes a cheap mix hash (splitmix-style)
    that XLA vectorizes; num_hash rows per input."""
    x = ins["X"][0].astype(jnp.uint32)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 100000007))
    outs = []
    for i in range(num_hash):
        # murmur3-style 32-bit finalizer, seeded per hash row (works in
        # JAX's default 32-bit int mode; wraparound is the point)
        h = x + jnp.uint32(((i + 1) * 0x9E3779B9) & 0xFFFFFFFF)
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int32))
    out = jnp.stack(outs, axis=-2) if num_hash > 1 else outs[0]
    return {"Out": [out]}


@register("pool2d_with_index")
def _pool2d_with_index(ctx, ins, attrs):
    """max_pool2d_with_index (pool_with_index_op.cc): max pool + argmax
    mask (flat h*w index per window), used by unpooling nets."""
    x = ins["X"][0]
    ks = attrs.get("ksize", [2, 2])
    st = attrs.get("strides", ks)
    n, c, h, w = x.shape
    kh, kw = int(ks[0]), int(ks[1])
    sh, sw = int(st[0]), int(st[1])
    oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )  # [n, c*kh*kw, oh, ow]
    patches = patches.reshape(n, c, kh * kw, oh, ow)
    out = jnp.max(patches, axis=2)
    arg = jnp.argmax(patches, axis=2)  # index within window
    # convert to flat input index (reference mask semantics)
    wy, wx = arg // kw, arg % kw
    oy = jnp.arange(oh).reshape(1, 1, -1, 1)
    ox = jnp.arange(ow).reshape(1, 1, 1, -1)
    flat = (oy * sh + wy) * w + (ox * sw + wx)
    return {"Out": [out], "Mask": [flat.astype(jnp.int32)]}


@register("lod_reset", no_grad_inputs=("Y",))
def _lod_reset(ctx, ins, attrs):
    """lod_reset_op.cc: in the padded representation the data is unchanged;
    the new boundary info is the (optional) Y lengths tensor, which callers
    thread as the new seq_len."""
    return {"Out": [ins["X"][0]]}


@register("delete_var", side_effect=True)
def _delete_var(ctx, ins, attrs):
    """delete_var_op.cc: explicit free — a no-op under XLA buffer liveness
    (kept so transpiled reference programs run)."""
    return {}


# ---------------------------------------------------------------------------
# sequence utilities (padded+lengths forms of sequence_ops/*)
# ---------------------------------------------------------------------------
@register("sequence_enumerate", no_grad_inputs=("X",))
def _sequence_enumerate(ctx, ins, attrs):
    """sequence_enumerate_op.cc: sliding win_size id windows per step,
    pad_value beyond the end. X: [B, T] int -> Out: [B, T, win]."""
    x = ins["X"][0]
    win = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    b, t = x.shape[:2]
    cols = []
    for k in range(win):
        shifted = jnp.concatenate(
            [x[:, k:], jnp.full((b, k), pad, x.dtype)], axis=1
        )
        cols.append(shifted)
    return {"Out": [jnp.stack(cols, axis=-1)]}


@register("sequence_erase", no_grad_inputs=("X",))
def _sequence_erase(ctx, ins, attrs):
    """sequence_erase_op.cc re-expressed for static shapes: erased tokens
    are masked to pad (0) and compacted to the front of each row, with the
    new lengths emitted as OutLen."""

    x = ins["X"][0]
    tokens = jnp.asarray(list(attrs.get("tokens", [])), x.dtype)
    keep = jnp.all(x[..., None] != tokens.reshape((1,) * x.ndim + (-1,)), axis=-1)
    compacted, new_len = stable_compact(keep, x, axis=1)
    return {"Out": [compacted], "OutLen": [new_len.astype(jnp.int64)]}


@register("sequence_expand_as")
def _sequence_expand_as(ctx, ins, attrs):
    """sequence_expand_as_op.cc: tile each row of X along Y's time axis."""
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == y.ndim:
        return {"Out": [jnp.broadcast_to(x, y.shape)]}
    out = jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])
    return {"Out": [out]}


@register("sequence_scatter", no_grad_inputs=("Ids",))
def _sequence_scatter(ctx, ins, attrs):
    """sequence_scatter_op.cc: scatter-add Updates rows into X at per-row
    time indices Ids.  X: [B, T], Ids/Updates: [B, K]."""
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    b = x.shape[0]
    rows = jnp.arange(b)[:, None].astype(jnp.int32)
    rows = jnp.broadcast_to(rows, ids.shape)
    return {"Out": [x.at[rows, ids.astype(jnp.int32)].add(upd)]}


# ---------------------------------------------------------------------------
# rnn units (gru_unit_op.cc)
# ---------------------------------------------------------------------------
@register("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """One GRU step: Input [B, 3H] (pre-projected), HiddenPrev [B, H],
    Weight [H, 3H] (update|reset | candidate), optional Bias [3H]."""
    x = ins["Input"][0]
    h_prev = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    hdim = h_prev.shape[-1]
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    if bias is not None:
        x = x + bias
    acts = {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda v: v,
        "hard_sigmoid": lambda v: jnp.clip(0.2 * v + 0.5, 0.0, 1.0),
    }
    gate_act = acts[attrs.get("gate_activation", "sigmoid")]
    cand_act = acts[attrs.get("activation", "tanh")]
    gate_w = w[:, : 2 * hdim]
    cand_w = w[:, 2 * hdim :]
    gates = x[:, : 2 * hdim] + h_prev @ gate_w
    u = gate_act(gates[:, :hdim])
    r = gate_act(gates[:, hdim:])
    c = cand_act(x[:, 2 * hdim :] + (r * h_prev) @ cand_w)
    # gru_unit_op.h:116: h = u * (c - h_prev) + h_prev = u*c + (1-u)*h_prev
    h = u * c + (1.0 - u) * h_prev
    return {"Gate": [gates], "ResetHiddenPrev": [r * h_prev], "Hidden": [h]}


# ---------------------------------------------------------------------------
# metric ops (detection_map_op.cc, positive_negative_pair_op.cc)
# ---------------------------------------------------------------------------
@register("positive_negative_pair", no_grad_inputs=("Score", "Label", "QueryID"))
def _positive_negative_pair(ctx, ins, attrs):
    """Pairwise ranking quality per query: counts of correctly/incorrectly
    ordered pairs (+ties) — learning-to-rank eval."""
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    query = ins["QueryID"][0].reshape(-1)
    n = score.shape[0]
    same_q = query[:, None] == query[None, :]
    li, lj = label[:, None], label[None, :]
    si, sj = score[:, None], score[None, :]
    valid = same_q & (li > lj)
    pos = jnp.sum((valid & (si > sj)).astype(jnp.float32))
    neg = jnp.sum((valid & (si < sj)).astype(jnp.float32))
    neu = jnp.sum((valid & (si == sj)).astype(jnp.float32))
    return {
        "PositivePair": [pos.reshape(1)],
        "NegativePair": [neg.reshape(1)],
        "NeutralPair": [neu.reshape(1)],
    }


# ---------------------------------------------------------------------------
# op-level save / load (save_op.cc, load_op.cc, *_combine): host callbacks
# so reference-style programs that embed checkpoint ops run unchanged
# ---------------------------------------------------------------------------
def _save_path(attrs):
    import os

    path = attrs["file_path"]
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return path


@register("save", side_effect=True)
def _save(ctx, ins, attrs):
    from jax.experimental import io_callback

    path = _save_path(attrs)

    def host_save(x):
        np.save(path + ".npy" if not path.endswith(".npy") else path, np.asarray(x))
        return np.int32(0)

    tok = io_callback(
        host_save, jax.ShapeDtypeStruct((), jnp.int32), ins["X"][0], ordered=True
    )
    return {"Out": [tok]}


@register("load", side_effect=True)
def _load(ctx, ins, attrs):
    from jax.experimental import io_callback

    path = attrs["file_path"]
    arr = np.load(path + ".npy" if not path.endswith(".npy") else path)

    def host_load():
        return np.load(path + ".npy" if not path.endswith(".npy") else path)

    out = io_callback(
        host_load, jax.ShapeDtypeStruct(arr.shape, arr.dtype), ordered=True
    )
    return {"Out": [out]}


@register("save_combine", side_effect=True)
def _save_combine(ctx, ins, attrs):
    from jax.experimental import io_callback

    path = _save_path(attrs)
    names = list(attrs.get("var_names", [str(i) for i in range(len(ins["X"]))]))

    def host_save(*arrs):
        np.savez(path, **{n: np.asarray(a) for n, a in zip(names, arrs)})
        return np.int32(0)

    tok = io_callback(
        host_save, jax.ShapeDtypeStruct((), jnp.int32), *ins["X"], ordered=True
    )
    return {"Out": [tok]}


@register("load_combine", side_effect=True)
def _load_combine(ctx, ins, attrs):
    from jax.experimental import io_callback

    path = attrs["file_path"]
    if not path.endswith(".npz"):
        path = path + ".npz"
    blob = np.load(path)
    names = list(attrs.get("var_names", list(blob.files)))
    outs = []
    for n in names:
        arr = blob[n]

        def host_load(n=n):
            return np.load(path)[n]

        outs.append(
            io_callback(
                host_load, jax.ShapeDtypeStruct(arr.shape, arr.dtype), ordered=True
            )
        )
    return {"Out": outs}


@register("sampling_id", no_grad_inputs=("X",), needs_rng=True)
def _sampling_id(ctx, ins, attrs):
    """sampling_id_op.cc: sample one category id per row from a
    probability matrix (device-side RNG instead of the reference's host
    std::mt19937)."""
    x = ins["X"][0]  # [B, C] probabilities
    key = ctx.rng(attrs)
    logits = jnp.log(jnp.maximum(x, 1e-20))
    ids = jax.random.categorical(key, logits, axis=-1)
    return {"Out": [ids.astype(jnp.int32)]}


@register("sequence_slice", no_grad_inputs=("Offset", "Length"))
def _sequence_slice(ctx, ins, attrs):
    """sequence_slice_op.cc re-expressed for the padded representation:
    each row b of X keeps the window [Offset[b], Offset[b]+Length[b]) of
    its time axis, shifted to the front; positions past the new length are
    zeroed.  New per-row lengths are emitted as OutLen (the LoD analog)."""
    x = ins["X"][0]
    offset = ins["Offset"][0].reshape(-1).astype(jnp.int32)
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    # truncate out-of-range windows at the tensor bound (the reference
    # enforces offset+length <= seq_len; here the honest equivalent is a
    # clamped window with the clamped length reported in OutLen, never
    # duplicated frames presented as valid data)
    offset = jnp.clip(offset, 0, t)
    eff_len = jnp.clip(length, 0, t - offset)
    idx = offset[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B, T]
    idx = jnp.clip(idx, 0, t - 1)
    gather_idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    gather_idx = jnp.broadcast_to(gather_idx, (x.shape[0], t) + x.shape[2:])
    out = jnp.take_along_axis(x, gather_idx, axis=1)
    mask = jnp.arange(t, dtype=jnp.int32)[None, :] < eff_len[:, None]
    out = jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)), out, 0)
    return {"Out": [out], "OutLen": [eff_len.astype(jnp.int64)]}


@register("unfold")
def _unfold(ctx, ins, attrs):
    """unfold_op (im2col as an op): NCHW -> [N, C*kh*kw, L] sliding-window
    patches.  The reference does explicit im2col on the host kernel; on TPU
    XLA's conv_general_dilated_patches keeps it one fused gather."""
    x = ins["X"][0]
    ksizes = [int(k) for k in attrs["kernel_sizes"]]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    if len(paddings) == 2:
        pad = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    else:  # [top, left, bottom, right] per the reference attr layout
        pad = [(paddings[0], paddings[2]), (paddings[1], paddings[3])]
    patches = jax.lax.conv_general_dilated_patches(
        x,
        filter_shape=ksizes,
        window_strides=strides,
        padding=pad,
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, H', W']
    n, ckk = patches.shape[:2]
    return {"Y": [patches.reshape(n, ckk, -1)]}


@register("cond_take", no_grad_inputs=("Mask",))
def _cond_take(ctx, ins, attrs):
    """cond_op-style masked take with static shapes: elements of X where
    Mask is true, stably compacted to the front of a full-size buffer
    (zero-padded), plus the true count — the TPU answer to the
    dynamic-output-size CondOp/masked-select pattern."""

    x = ins["X"][0].reshape(-1)
    keep = ins["Mask"][0].reshape(-1).astype(bool)
    taken, count = stable_compact(keep, x, axis=0)
    return {"Out": [taken], "Count": [count.astype(jnp.int64).reshape(1)]}
