"""Per-(kernel, shape-bucket) block-size tuning cache for the Pallas
kernel layer (the seed of the TVM-style autotuner, ROADMAP item 2).

Every `pallas_call` site in ops/pallas_kernels.py picks its block sizes
through `tuned_params`: the discrete knob space (block_q/block_k,
block_rows, matmul tiles...) is a *searched, cached* decision instead of
a hand-pick.  Keys are (kernel, shape bucket, dtype, device kind);
values are the winning params plus provenance (searched vs seeded) and
the measured search cost.  The cache persists as JSON at
FLAGS_kernel_tune_cache, so a fleet warms once per shape bucket and
every later process (or CI, with a pinned cache and
FLAGS_kernel_autotune=0) dispatches without ever searching.

Search happens at FIRST REAL-DEVICE DISPATCH: lowering runs under a jax
trace, so candidates are timed on synthetic operands of the call-site
shapes through a standalone jit of the kernel (compile-time work — the
model step itself is never perturbed).  In interpret mode (CPU tests)
timings are meaningless, so misses seed the heuristic default and are
counted, never searched.

Attribution counters (`note_kernel` / `attribution()`): per-family
pallas-hit counts and tuning hit/miss/search totals, read by bench.py so
an MFU regression can be pinned to "kernel X stopped dispatching" or
"cache went cold" instead of guessed at.  Counts tick at TRACE time
(once per compiled program, not per step) — they attribute what the
compiled step contains, not how often it runs.
"""

import threading
import time

__all__ = [
    "tuned_params",
    "shape_bucket",
    "note_kernel",
    "attribution",
    "reset_attribution",
    "measure_candidate",
    "cache_stats",
    "clear_cache",
]

_lock = threading.RLock()
_cache = None  # key -> {"params": {...}, "searched": bool, "search_ms": float}
_cache_path = None  # path the in-memory cache was loaded from
_stats = {"hits": 0, "misses": 0, "searches": 0, "search_ms": 0.0}
_kernel_hits = {}  # family -> pallas dispatch count (trace-time)
_searching = threading.local()  # candidate timing in flight on this thread
_inflight = {}  # key -> threading.Event: a measured search under way


def _flag(name):
    from ..flags import get_flag

    return get_flag(name)


def _device_kind():
    """Stable device identity for cache keys; interpret-mode (CPU) runs
    are their own universe so a CI cache never leaks onto a real chip."""
    import jax

    try:
        d = jax.devices()[0]
    except RuntimeError:
        return "unknown"
    if d.platform not in ("tpu", "axon"):
        return "interpret-%s" % d.platform
    return (getattr(d, "device_kind", "") or d.platform).replace(" ", "_")


def _pow2_bucket(n):
    n = int(n)
    if n <= 1:
        return 1
    p = 1
    while p < n:
        p *= 2
    return p


def shape_bucket(shapes):
    """Canonical bucket string: leading (row/batch) dims round up to the
    next power of two — one searched entry serves every batch in the
    bucket — while the last (feature/lane) dim of each operand stays
    exact, since it decides Mosaic legality and VMEM footprint."""
    parts = []
    for shape in shapes:
        dims = [int(d) for d in shape]
        if len(dims) <= 1:
            parts.append("x".join(str(d) for d in dims))
        else:
            parts.append("x".join(
                [str(_pow2_bucket(d)) for d in dims[:-1]]
                + [str(dims[-1])]))
    return ",".join(parts)


def _key(kernel, shapes, dtype):
    return "|".join([kernel, shape_bucket(shapes), str(dtype),
                     _device_kind()])


def _entry_valid(v):
    return isinstance(v.get("params"), dict)


def _load_locked():
    global _cache, _cache_path
    from ..utils.tune_cache import load_entries

    path = str(_flag("kernel_tune_cache") or "")
    if _cache is not None and path == _cache_path:
        return
    _cache_path = path
    _cache = load_entries(path, _entry_valid, "kernel tuning cache")


def _save_locked():
    # searched decisions only, merged with concurrent writers' searched
    # entries, atomic replace — the shared utils.tune_cache discipline
    # (a seeded default, including one left behind by a failed search,
    # stays process-local so the next process re-searches; a pinned CI
    # cache never gains entries)
    from ..utils.tune_cache import save_entries

    save_entries(_cache_path, _cache, _entry_valid,
                 "kernel tuning cache")


def _search_allowed(measure):
    """Measured search only when explicitly injected (tests) or running
    on a real accelerator with FLAGS_kernel_autotune on."""
    if not _flag("kernel_autotune"):
        return False
    if measure is not None:
        return True
    from .pallas_kernels import _interpret

    return not _interpret()


def measure_candidate(build_fn, arg_specs, warmup=1, iters=3, seed=0):
    """Default measurer: time `build_fn(params)` — a callable over
    positional arrays — on synthetic operands of `arg_specs`
    [(shape, dtype), ...].  Returns median seconds/call (compiled,
    block_until_ready).  Raises whatever the candidate raises, so the
    caller can skip illegal block configurations.  Operands materialize
    LAZILY at the first timing call: a measurer is constructed on every
    real-device consult, almost all of which are cache hits that never
    measure — building full-size device arrays up front would burn HBM
    and transfer time for nothing."""
    import jax

    state = {}

    def _args():
        import numpy as np

        if "args" not in state:
            rng = np.random.RandomState(seed)
            args = []
            for shape, dtype in arg_specs:
                if str(dtype).startswith("int"):
                    args.append(jax.numpy.asarray(
                        rng.randint(0, 2, size=shape), dtype=dtype))
                else:
                    args.append(jax.numpy.asarray(
                        rng.randn(*shape) * 0.1, dtype=dtype))
            state["args"] = args
        return state["args"]

    def run(fn):
        out = fn(*_args())
        jax.block_until_ready(out)
        return out

    def bench(params):
        fn = jax.jit(build_fn(params))
        for _ in range(warmup):
            run(fn)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            run(fn)
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    return bench


def tuned_params(kernel, shapes, dtype, candidates, default, measure=None):
    """The one entry point: returns the block-size params dict for this
    (kernel, shapes, dtype) call site.

    candidates: list of param dicts (the discrete search space; may be
    empty).  default: the heuristic params used when no search runs.
    measure: optional params -> seconds callable (injected by tests and
    by real-device call sites via `measure_candidate`); a candidate that
    raises is skipped (illegal block shapes surface as compile errors).

    Cache hit -> cached params.  Miss -> search when allowed (real
    device or injected measure, FLAGS_kernel_autotune on), else seed the
    default; either way the decision is recorded (and persisted when
    FLAGS_kernel_tune_cache names a file) so it is made once per shape
    bucket per device kind."""
    with _lock:
        _load_locked()
        key = _key(kernel, shapes, dtype)
        hit = _cache.get(key)
        if hit is not None:
            _stats["hits"] += 1
            return dict(hit["params"])
        if not (candidates and _search_allowed(measure)):
            _stats["misses"] += 1
            entry = {"params": dict(default), "searched": False,
                     "search_ms": 0.0}
            _cache[key] = entry
            return dict(entry["params"])
        waiter = _inflight.get(key)
        if waiter is None:
            _inflight[key] = threading.Event()
            _stats["misses"] += 1

    if waiter is not None:
        # another thread is measuring this key: wait for its decision
        # instead of racing a duplicate search (the timeout is a hedge
        # against a searcher dying without its finally — fall back to
        # the heuristic default rather than hang the trace)
        waiter.wait(timeout=600.0)
        with _lock:
            hit = _cache.get(key)
            if hit is not None:
                _stats["hits"] += 1
                return dict(hit["params"])
        return dict(default)

    # measure OUTSIDE the lock: a search is compile + warmup + timed
    # runs per candidate (seconds to minutes on a real chip) and must
    # not serialize other threads' consults — cache hits for unrelated
    # kernels keep flowing while this key searches
    entry = {"params": dict(default), "searched": False, "search_ms": 0.0}
    ms = 0.0
    try:
        t0 = time.perf_counter()
        best, best_t = dict(default), None
        # candidate compiles re-trace the kernel bodies: mute the
        # per-family hit counters meanwhile, or one searched miss
        # with N candidates would report N phantom dispatches and
        # corrupt the bench attribution
        _searching.active = True
        try:
            for cand in candidates:
                try:
                    t = measure(dict(cand))
                except Exception:  # illegal blocks: skip, search on
                    continue
                if best_t is None or t < best_t:
                    best, best_t = dict(cand), t
        finally:
            _searching.active = False
        ms = (time.perf_counter() - t0) * 1e3
        if best_t is not None:
            entry = {"params": best, "searched": True,
                     "search_ms": round(ms, 3)}
    finally:
        with _lock:
            _cache[key] = entry
            if entry["searched"]:
                _stats["searches"] += 1
                _stats["search_ms"] += ms
                # only measured decisions persist: seeded defaults are
                # deterministic heuristics (nothing to remember), and a
                # CI run against a pinned read-only cache must not
                # dirty it
                _save_locked()
            ev = _inflight.pop(key, None)
            if ev is not None:
                ev.set()
    return dict(entry["params"])


def note_kernel(family, n=1):
    """Count a pallas dispatch for `family` (attention / matmul-epilogue
    / xent / layernorm / recurrent).  Trace-time counter; muted while a
    block-size search times candidates (those traces are not program
    content)."""
    if getattr(_searching, "active", False):
        return
    with _lock:
        _kernel_hits[family] = _kernel_hits.get(family, 0) + n


def attribution():
    """Snapshot for bench attribution: per-family pallas-hit counts plus
    tuning-cache hit/miss/search totals (search_ms summed)."""
    with _lock:
        return {
            "pallas_hits": dict(_kernel_hits),
            "tuning": {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in _stats.items()},
        }


def reset_attribution():
    with _lock:
        _kernel_hits.clear()
        _stats.update({"hits": 0, "misses": 0, "searches": 0,
                       "search_ms": 0.0})


def cache_stats():
    """Entry count + path of the live cache (for tests/diagnostics)."""
    with _lock:
        _load_locked()
        return {"entries": len(_cache), "path": _cache_path,
                "searched": sum(1 for v in _cache.values()
                                if v.get("searched"))}


def clear_cache(forget_path=False):
    """Drop the in-memory cache (tests); the on-disk file is untouched.
    forget_path also resets the load marker so the next consult reloads
    from FLAGS_kernel_tune_cache."""
    global _cache, _cache_path
    with _lock:
        _cache = None if forget_path else {}
        if forget_path:
            _cache_path = None
