"""In-step collective op lowerings: the `c_allreduce_*` family.

The reference synchronizes dense gradients in collective ("nccl2") mode
with runtime NCCL ops (operators/collective/c_allreduce_op.h,
c_allreduce_sum_op.cc) that ParallelExecutor schedules between the
backward and the optimizer.  Here the same rewrite — DistributeTranspiler
``mode="collective"`` inserts one allreduce between each dense ``*_grad``
output and its optimizer op — lowers to ``jax.lax`` collectives traced
INTO the one jitted step (core/trace.py), so XLA overlaps the all-reduce
with backward compute and no Python runs in the dense-grad path at all.

Axis binding: the collective run path (executor._run_collective) traces
the step under ``shard_map`` over a ``parallel/mesh.dp_mesh`` and enters
``parallel.collective.collective_lowering`` so these rules see the bound
axis.  Traced WITHOUT that context (a transpiled program run on a plain
executor, or a single-device mesh) the ops degrade to single-replica
semantics — allreduce over a world of one is the identity — so the same
program trains standalone.

``Deps`` (hybrid pserver mode): sparse ``send_sparse`` tokens are threaded
through the allreduce via ``lax.optimization_barrier``, making the psum —
a cross-device rendezvous — wait for every replica's sparse push.  With
the next step's ``prefetch`` depending on an allreduced-update param
(its own ``Dep`` input), every replica's step-N sparse rows land on the
pserver before ANY replica's step-N+1 lookup reads them: the ordering the
pserver round barrier used to provide, rebuilt from pure data flow.
"""

import jax.lax as lax

from ..core.registry import register
from ..parallel import collective


def _tie(x, deps):
    """Data-dependency barrier: make `x` depend on every token in `deps`
    without changing its value (optimization_barrier outputs depend on
    ALL inputs — XLA cannot reorder past it or elide the tokens)."""
    if not deps:
        return x
    tied = lax.optimization_barrier(tuple([x] + list(deps)))
    return tied[0]


def _allreduce(ins, attrs, op):
    x = _tie(ins["X"][0], ins.get("Deps", ()))
    bound = collective.lowering_axis()
    if bound is None:
        # single-replica semantics: sum/mean over a world of one
        return {"Out": [x]}
    axis, _nranks = bound
    want = attrs.get("axis_name")
    if want and str(want) != axis:
        raise ValueError(
            "c_allreduce planned for axis %r but the collective trace "
            "bound %r — transpile and run over the same mesh axis"
            % (want, axis))
    return {"Out": [collective.all_reduce(x, axis, op=op)]}


@register("c_allreduce_sum")
def _c_allreduce_sum(ctx, ins, attrs):
    """Cross-replica gradient sum (c_allreduce_sum_op.cc analog)."""
    return _allreduce(ins, attrs, "sum")


@register("c_allreduce_mean")
def _c_allreduce_mean(ctx, ins, attrs):
    """Cross-replica gradient mean: each replica's grad is its local
    shard-mean, so the mean across replicas IS the global-batch mean
    gradient — the transpiler's default dense-grad rewrite (the pserver
    path's scale-by-1/N-then-sum, fused into one collective)."""
    return _allreduce(ins, attrs, "mean")


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py): collectives are shape/dtype
# transparent — one tensor in, the reduced tensor out
# ---------------------------------------------------------------------------
from ..analysis.infer import register_infer, same_as  # noqa: E402

for _name in ("c_allreduce_sum", "c_allreduce_mean"):
    register_infer(_name, req_ins=("X",))(same_as("X"))
