"""shard_map dispatch for the matmul-epilogue pallas kernels under a
GSPMD mesh — closing the PR 14 documented limit that the epilogue
kernels operand-replicate inside a sharded step.

``pallas_call`` has no SPMD partition rule: inside a GSPMD-stamped
program an unwrapped kernel forces XLA to all-gather every operand onto
each device, run the full kernel everywhere, and throw n-1 copies of
the work away.  The qvec-attention lowering already solved this for the
ragged serving step (``_qvec_attention_mesh``); this module generalizes
the recipe to the fc / fused_swiglu / fused_residual_ln /
fused_linear_xent lowerings:

1. resolve the op's WEIGHT NAMES from the OpDesc being lowered
   (``ctx.block.ops[ctx.op_idx]`` — the grad-side re-run of a forward
   rule sees the same block through ``lower_grad_op``),
2. look the names up in the live rule table (``current_spmd``) to
   classify the layout — column-parallel, row-parallel, vocab-sharded,
   or replicated-weights-with-dp-sharded-rows,
3. run the SAME custom_vjp kernel per shard inside ``shard_map`` with
   matching in/out specs.  ``check_rep=False`` autodiff supplies the
   transpose-side psums for replicated operands; the only hand-written
   collectives are the mathematical ones (the row-parallel epilogue's
   partial-sum psum, the vocab-sharded xent's lse/gold/sum combine).

Block sizes inside shard_map are the deterministic defaults computed
from the LOCAL shard shapes — a per-shard tuning search would attribute
collective time to block sizes (the qvec precedent).

Every wrapper returns None when it declines (no mesh, mp=1 and dp=1,
weight name unresolvable, layout not divisible) and the caller falls
back to the unwrapped kernel — at mp=1 that keeps the single-device
trace BIT-IDENTICAL.
"""

import jax
import jax.numpy as jnp

__all__ = [
    "mesh_ctx", "op_weight_name", "spmd_matmul_bias_act",
    "spmd_matmul_swiglu", "spmd_add_layer_norm", "spmd_linear_xent",
]


def mesh_ctx():
    """(mesh, rules, mp_axis, nsh, dp_axis, ndp) when tracing under a
    live spmd_lowering context with something to shard over, else
    None."""
    from ..parallel.mesh import mesh_axis_sizes
    from ..parallel.partition_rules import current_spmd

    spmd = current_spmd()
    if spmd is None:
        return None
    mesh, rules = spmd
    sizes = mesh_axis_sizes(mesh)
    mp = rules.mp_axis
    nsh = int(sizes.get(mp, 1))
    dp_axis = getattr(rules, "dp_axis", None)
    ndp = int(sizes.get(dp_axis, 1)) if dp_axis else 1
    if nsh <= 1 and ndp <= 1:
        return None
    return mesh, rules, mp, nsh, dp_axis, ndp


def op_weight_name(ctx, expected_type, slot):
    """The var name feeding `slot` of the op being lowered, resolved
    through ctx.block + ctx.op_idx ((block_idx << 20) | idx on the
    forward trace, the plain forward index on the grad-side re-run).
    None when the context carries no block or the op type disagrees —
    callers MUST fall back to the unwrapped kernel then."""
    blk = getattr(ctx, "block", None)
    if blk is None:
        return None
    idx = int(getattr(ctx, "op_idx", 0)) & ((1 << 20) - 1)
    if idx >= len(blk.ops):
        return None
    op = blk.ops[idx]
    if op.type != expected_type:
        return None
    names = op.input(slot)
    return names[0] if names else None


def _dim_has(spec, d, axis):
    """Does PartitionSpec `spec` place mesh axis `axis` on dim `d`?"""
    if spec is None or len(spec) <= d:
        return False
    e = tuple(spec)[d]
    return e == axis or (isinstance(e, tuple) and axis in e)


def _row_axis(dp_axis, ndp, rows):
    """The activation-rows mesh axis: the dp axis when it exists and
    divides the flattened row count, else None (rows replicate)."""
    return dp_axis if (dp_axis and ndp > 1 and rows % ndp == 0) else None


def _shard_map(mesh, body, in_specs, out_specs):
    from ..parallel.mesh import shard_map

    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def spmd_matmul_bias_act(ctx, x2, w, bias, act):
    """Mesh-aware matmul_bias_act: column-parallel (w P(·, mp): local
    columns, no collective — bias slices with its column), row-parallel
    (w P(mp, ·): partial sums psum'd, bias + act applied AFTER the
    combine), or replicated-w with dp-sharded rows.  None -> unwrapped."""
    from jax.sharding import PartitionSpec as P

    from .pallas_kernels import _mm_act, _mm_col_block, _row_block, \
        matmul_bias_act

    mc = mesh_ctx()
    if mc is None:
        return None
    mesh, rules, mp, nsh, dp_axis, ndp = mc
    wname = op_weight_name(ctx, "fc", "W")
    if wname is None:
        return None
    spec = rules.spec_for(wname, tuple(w.shape))
    M, K = x2.shape
    N = w.shape[1]
    row = _row_axis(dp_axis, ndp, M)
    nrow = ndp if row else 1
    col_par = nsh > 1 and _dim_has(spec, 1, mp) and N % nsh == 0
    row_par = nsh > 1 and _dim_has(spec, 0, mp) and K % nsh == 0

    if col_par:
        bm = _row_block(M // nrow, 256)
        bn = _mm_col_block(N // nsh, 256)

        def body(xl, wl, bl):
            return matmul_bias_act(xl, wl, bl, act, bm, bn)

        in_specs = (P(row, None), P(None, mp), P(mp))
        out_spec = P(row, mp)
        if bias is None:
            body, in_specs = (lambda xl, wl:
                              matmul_bias_act(xl, wl, None, act, bm, bn)
                              ), in_specs[:2]
            return _shard_map(mesh, body, in_specs, out_spec)(x2, w)
        return _shard_map(mesh, body, in_specs, out_spec)(x2, w, bias)

    if row_par:
        bm = _row_block(M // nrow, 256)
        bn = _mm_col_block(N, 256)

        def body(xl, wl, *b):
            z = matmul_bias_act(xl, wl, None, "", bm, bn)
            z = jax.lax.psum(z.astype(jnp.float32), mp)
            if b:
                z = z + b[0].reshape(1, -1).astype(jnp.float32)
            return _mm_act(z, act).astype(xl.dtype)

        in_specs = (P(row, mp), P(mp, None))
        args = (x2, w)
        if bias is not None:
            in_specs = in_specs + (P(None),)
            args = args + (bias,)
        return _shard_map(mesh, body, in_specs, P(row, None))(*args)

    if row is None:
        return None
    bm = _row_block(M // nrow, 256)
    bn = _mm_col_block(N, 256)

    def body(xl, wl, *b):
        return matmul_bias_act(xl, wl, b[0] if b else None, act, bm, bn)

    in_specs = (P(row, None), P(None, None))
    args = (x2, w)
    if bias is not None:
        in_specs = in_specs + (P(None),)
        args = args + (bias,)
    return _shard_map(mesh, body, in_specs, P(row, None))(*args)


def spmd_matmul_swiglu(ctx, x2, wg, wu):
    """Mesh-aware matmul_swiglu: the gate/up pair is column-parallel
    when BOTH weights carry P(·, mp) (silu and the product are
    element-wise in the sharded column space); otherwise rows-only when
    dp divides."""
    from jax.sharding import PartitionSpec as P

    from .pallas_kernels import _mm_col_block, _row_block, matmul_swiglu

    mc = mesh_ctx()
    if mc is None:
        return None
    mesh, rules, mp, nsh, dp_axis, ndp = mc
    gname = op_weight_name(ctx, "fused_swiglu", "GateW")
    uname = op_weight_name(ctx, "fused_swiglu", "UpW")
    if gname is None or uname is None:
        return None
    gspec = rules.spec_for(gname, tuple(wg.shape))
    uspec = rules.spec_for(uname, tuple(wu.shape))
    M, K = x2.shape
    N = wg.shape[1]
    row = _row_axis(dp_axis, ndp, M)
    nrow = ndp if row else 1
    col_par = (nsh > 1 and N % nsh == 0
               and _dim_has(gspec, 1, mp) and _dim_has(uspec, 1, mp))
    if not col_par and (row is None or _dim_has(gspec, 1, mp)
                        or _dim_has(uspec, 1, mp)):
        return None
    wspec = P(None, mp) if col_par else P(None, None)
    ncol = nsh if col_par else 1
    bm = _row_block(M // nrow, 256)
    bn = _mm_col_block(N // ncol, 256)

    def body(xl, wgl, wul):
        return matmul_swiglu(xl, wgl, wul, bm, bn)

    return _shard_map(
        mesh, body, (P(row, None), wspec, wspec),
        P(row, mp) if col_par else P(row, None))(x2, wg, wu)


def spmd_add_layer_norm(ctx, x2, y2, gamma, beta, eps):
    """Mesh-aware fused_add_layer_norm: rows are independent, so the
    kernel shards over dp rows with gamma/beta replicated.  (The hidden
    axis never shards in the decoder tables — LN reduces over it.)"""
    from jax.sharding import PartitionSpec as P

    from .pallas_kernels import _row_block, fused_add_layer_norm

    mc = mesh_ctx()
    if mc is None:
        return None
    mesh, rules, mp, nsh, dp_axis, ndp = mc
    row = _row_axis(dp_axis, ndp, x2.shape[0])
    if row is None:
        return None
    br = _row_block(x2.shape[0] // ndp, 256)

    def body(xl, yl, g, b):
        return fused_add_layer_norm(xl, yl, g, b, eps, br)

    rs = P(row, None)
    return _shard_map(mesh, body, (rs, rs, P(None), P(None)),
                      (rs, rs))(x2, y2, gamma, beta)


def spmd_linear_xent(ctx, x2, w, labels, eps, transpose_w):
    """Mesh-aware fused_linear_xent: when the projection weight is
    vocab-sharded (softmax_out.w P(None, mp), or tied emb.w P(mp, None)
    arriving transposed), each shard streams its own [H, V/n] slab
    through sharded_linear_xent — per-row scalar collectives combine
    the shards' online (lse, gold, sum).  Rows additionally shard over
    dp.  `w` is the value ALREADY transposed to [H, V]; `transpose_w`
    says which dim of the DECLARED weight the rule table sees as
    vocab."""
    from jax.sharding import PartitionSpec as P

    from .pallas_kernels import _lxent_default_blocks, fused_linear_xent, \
        sharded_linear_xent

    mc = mesh_ctx()
    if mc is None:
        return None
    mesh, rules, mp, nsh, dp_axis, ndp = mc
    wname = op_weight_name(ctx, "fused_linear_xent", "W")
    if wname is None:
        return None
    decl_shape = tuple(w.shape[::-1]) if transpose_w else tuple(w.shape)
    spec = rules.spec_for(wname, decl_shape)
    vdim = 0 if transpose_w else 1
    R, H = x2.shape
    V = w.shape[1]
    if _dim_has(spec, 1 - vdim, mp):
        return None  # hidden-sharded projection: not a supported layout
    vocab_sharded = nsh > 1 and _dim_has(spec, vdim, mp) and V % nsh == 0
    row = _row_axis(dp_axis, ndp, R)
    nrow = ndp if row else 1
    if not vocab_sharded and row is None:
        return None

    if vocab_sharded:
        br, bv = _lxent_default_blocks(R // nrow, H, V // nsh)

        def body(xl, wl, ll):
            return sharded_linear_xent(xl, wl, ll.reshape(-1), eps, mp,
                                       V, br, bv)

        return _shard_map(
            mesh, body, (P(row, None), P(None, mp), P(row)),
            P(row, None))(x2, w, labels.reshape(R))

    br, bv = _lxent_default_blocks(R // nrow, H, V)

    def body(xl, wl, ll):
        return fused_linear_xent(xl, wl, ll.reshape(-1), eps, br, bv)

    return _shard_map(
        mesh, body, (P(row, None), P(None, None), P(row)),
        P(row, None))(x2, w, labels.reshape(R))
