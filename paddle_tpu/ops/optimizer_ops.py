"""Optimizer op lowerings (operators/optimizers/*).

Each optimizer step is an op over (param, grad, accumulators) -> updated
tensors, matching the reference's per-param optimizer-op design
(``sgd_op.cc``, ``momentum_op.cc``, ``adam_op.cc``...).  In the compiled
step, XLA fuses all per-param updates into the training executable; donation
makes them in-place in HBM.  None of these are differentiated
(no_grad by construction: optimizer ops sit after backward).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.selected_rows import SelectedRows


def _lr(ins):
    lr = ins["LearningRate"][0]
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register("sgd", no_grad_inputs=("Param", "Grad", "LearningRate"),
          handles_selected_rows=True)
def _sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    if isinstance(g, SelectedRows):
        # sparse branch (sgd_op.h SelectedRows kernel): scatter-add only
        # the touched rows; duplicates sum linearly so no merge needed
        upd = -_lr(ins) * g.value.astype(p.dtype)
        return {"ParamOut": [p.at[g.rows].add(upd, mode="drop")]}
    return {"ParamOut": [p - _lr(ins) * g.astype(p.dtype)]}


@register("momentum", no_grad_inputs=("Param", "Grad", "Velocity", "LearningRate"),
          handles_selected_rows=True)
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    if isinstance(g, SelectedRows):
        # sparse branch (momentum_op.h SparseMomentumFunctor): the
        # reference densifies the merged rows (g=0 elsewhere) and runs
        # the dense rule over EVERY row — untouched rows still decay
        mer = g.merged()
        g = jnp.zeros_like(p).at[mer.rows].add(
            mer.value.astype(p.dtype), mode="drop")
    else:
        g = g.astype(p.dtype)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register(
    "lars_momentum", no_grad_inputs=("Param", "Grad", "Velocity", "LearningRate")
)
def _lars_momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    lr = _lr(ins)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * p_norm / (g_norm + decay * p_norm + 1e-15)
    v_out = mu * v + local_lr * (g + decay * p)
    return {"ParamOut": [p - v_out], "VelocityOut": [v_out]}


@register(
    "adam",
    no_grad_inputs=(
        "Param",
        "Grad",
        "Moment1",
        "Moment2",
        "Beta1Pow",
        "Beta2Pow",
        "LearningRate",
    ),
    handles_selected_rows=True,
)
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    if isinstance(g, SelectedRows):
        # sparse/lazy branch (adam_op.h SelectedRows kernel): moments decay
        # and update only on the touched rows; duplicates merged first
        # (non-linear in g).  Padding slots carry row == height -> dropped.
        mer = g.merged()
        rows, gv = mer.rows, mer.value.astype(p.dtype)
        m1r, m2r = m1[rows], m2[rows]
        m1n = beta1 * m1r + (1 - beta1) * gv
        m2n = beta2 * m2r + (1 - beta2) * jnp.square(gv)
        p_out = p.at[rows].add(-lr_t * m1n / (jnp.sqrt(m2n) + eps),
                               mode="drop")
        return {
            "ParamOut": [p_out],
            "Moment1Out": [m1.at[rows].set(m1n, mode="drop")],
            "Moment2Out": [m2.at[rows].set(m2n, mode="drop")],
            "Beta1PowOut": [b1p * beta1],
            "Beta2PowOut": [b2p * beta2],
        }
    g = g.astype(p.dtype)
    m1_out = beta1 * m1 + (1 - beta1) * g
    m2_out = beta2 * m2 + (1 - beta2) * jnp.square(g)
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    return {
        "ParamOut": [p_out],
        "Moment1Out": [m1_out],
        "Moment2Out": [m2_out],
        "Beta1PowOut": [b1p * beta1],
        "Beta2PowOut": [b2p * beta2],
    }


@register(
    "adamax",
    no_grad_inputs=("Param", "Grad", "Moment", "InfNorm", "Beta1Pow", "LearningRate"),
)
def _adamax(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, u = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    beta1, beta2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    m_out = beta1 * m + (1 - beta1) * g
    u_out = jnp.maximum(beta2 * u, jnp.abs(g))
    p_out = p - (lr / (1 - b1p.reshape(()))) * m_out / (u_out + eps)
    return {"ParamOut": [p_out], "MomentOut": [m_out], "InfNormOut": [u_out]}


@register("adagrad", no_grad_inputs=("Param", "Grad", "Moment", "LearningRate"),
          handles_selected_rows=True)
def _adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    lr = _lr(ins)
    if isinstance(g, SelectedRows):
        # sparse branch (adagrad_op.h SelectedRows kernel): merge duplicate
        # rows (m update is non-linear), then touch only those rows
        mer = g.merged()
        rows, gv = mer.rows, mer.value.astype(p.dtype)
        m_new = m[rows] + jnp.square(gv)
        p_out = p.at[rows].add(-lr * gv / (jnp.sqrt(m_new) + eps),
                               mode="drop")
        return {"ParamOut": [p_out],
                "MomentOut": [m.at[rows].set(m_new, mode="drop")]}
    m_out = m + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out], "MomentOut": [m_out]}


def _prox(x, lr, l1, l2):
    """Proximal operator for l1/l2 regularization
    (optimizers/proximal_gd_op.h update rule): soft-threshold by lr*l1,
    shrink by 1/(1 + lr*l2)."""
    if l1 > 0:
        x = jnp.sign(x) * jnp.maximum(jnp.abs(x) - lr * l1, 0.0)
    return x / (1.0 + lr * l2)


@register("proximal_gd", no_grad_inputs=("Param", "Grad", "LearningRate"))
def _proximal_gd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins)
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    return {"ParamOut": [_prox(p - lr * g.astype(p.dtype), lr, l1, l2)]}


@register(
    "proximal_adagrad",
    no_grad_inputs=("Param", "Grad", "Moment", "LearningRate"),
)
def _proximal_adagrad(ctx, ins, attrs):
    """optimizers/proximal_adagrad_op.h: adagrad prospective step
    (p - lr*g/sqrt(m+g^2)), then the proximal projection with the PLAIN
    lr (threshold lr*l1, shrink 1/(1+lr*l2)) — the reference applies the
    scalar lr in the prox, not the per-element adaptive step.  The g==0,
    m==0 corner returns a 0 step instead of the reference's 0/0."""
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = _lr(ins)
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    m_out = m + jnp.square(g)
    denom = jnp.sqrt(m_out)
    upd = jnp.where(denom > 0, g.astype(p.dtype) / denom, 0.0)
    return {"ParamOut": [_prox(p - lr * upd, lr, l1, l2)],
            "MomentOut": [m_out]}


@register(
    "decayed_adagrad", no_grad_inputs=("Param", "Grad", "Moment", "LearningRate")
)
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out], "MomentOut": [m_out]}


@register(
    "adadelta", no_grad_inputs=("Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate")
)
def _adadelta(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    asg, asu = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    asg_out = rho * asg + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((asu + eps) / (asg_out + eps)) * g
    asu_out = rho * asu + (1 - rho) * jnp.square(update)
    return {
        "ParamOut": [p + update],
        "AvgSquaredGradOut": [asg_out],
        "AvgSquaredUpdateOut": [asu_out],
    }


@register(
    "rmsprop",
    no_grad_inputs=("Param", "Grad", "Moment", "MeanSquare", "MeanGrad", "LearningRate"),
)
def _rmsprop(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    mom, ms = ins["Moment"][0], ins["MeanSquare"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    lr = _lr(ins)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get("centered", False):
        mg = ins["MeanGrad"][0]
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - jnp.square(mg_out) + eps
    else:
        mg_out = ins["MeanGrad"][0] if ins.get("MeanGrad") else jnp.zeros_like(p)
        denom = ms_out + eps
    mom_out = momentum * mom + lr * g / jnp.sqrt(denom)
    return {
        "ParamOut": [p - mom_out],
        "MomentOut": [mom_out],
        "MeanSquareOut": [ms_out],
        "MeanGradOut": [mg_out],
    }


@register(
    "ftrl",
    no_grad_inputs=("Param", "Grad", "SquaredAccumulator", "LinearAccumulator", "LearningRate"),
)
def _ftrl(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p_out = pre / denom
    return {
        "ParamOut": [p_out],
        "SquaredAccumOut": [new_sq],
        "LinearAccumOut": [new_lin],
    }


@register("model_average_accum", no_grad_inputs=("Param", "Sum", "Num", "NumUpdates"))
def _model_average_accum(ctx, ins, attrs):
    """ModelAverage accumulation (optimizer.py:1365): running param sum
    with window restart — the single-op re-expression of the reference's
    sum_1/sum_2/sum_3 rotation.  Reference restart rule: the window resets
    once it exceeds min(max_average_window, max(min_average_window,
    average_window_rate * total_updates))."""
    p = ins["Param"][0]
    s = ins["Sum"][0]
    n = ins["Num"][0]
    nu = ins["NumUpdates"][0] if ins.get("NumUpdates") else n
    rate = float(attrs.get("average_window_rate", 0.15))
    min_w = float(attrs.get("min_average_window", 10000))
    max_w = float(attrs.get("max_average_window", 10000))
    new_nu = nu + 1.0
    threshold = jnp.minimum(max_w, jnp.maximum(min_w, rate * new_nu))
    new_n = n + 1.0
    restart = new_n > threshold
    s_out = jnp.where(restart, p.astype(s.dtype), s + p.astype(s.dtype))
    n_out = jnp.where(restart, jnp.ones_like(n), new_n)
    return {"SumOut": [s_out], "NumOut": [n_out], "NumUpdatesOut": [new_nu]}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py): every optimizer out-slot
# `<X>Out` mirrors its in-slot `<X>` (in-place persistable updates)
# ---------------------------------------------------------------------------
from ..analysis.infer import VarInfo, register_infer  # noqa: E402


def _opt_infer(op, ins):
    outs = {}
    for slot in op.outputs:
        if not slot.endswith("Out"):
            continue
        src = ins.get(slot[:-len("Out")])
        if src and src[0] is not None:
            outs[slot] = [VarInfo(src[0].shape, src[0].dtype)]
    return outs


for _name in (
    "sgd", "momentum", "lars_momentum", "adam", "adamax", "adagrad",
    "proximal_gd", "proximal_adagrad", "rmsprop", "ftrl",
    "decayed_adagrad", "adadelta",
):
    register_infer(
        _name, req_ins=("Param", "Grad"), req_outs=("ParamOut",)
    )(_opt_infer)
