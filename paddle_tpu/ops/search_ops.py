"""Beam-search ops (operators/beam_search_op.cc, beam_search_decode_op.cc,
math/beam_search.cc).

The reference's beam search walks LoD levels per step inside a While loop
and decodes by joining LoD trees.  TPU-native contract: everything is
padded and batched — one step selects top-k over [batch, beam*vocab] with a
single jnp.top_k (MXU/VPU friendly), and decode is a reverse scan over the
stored parent pointers (the classic backpointer trick) instead of LoD tree
walking.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


@register("beam_search", no_grad_inputs=("pre_ids", "pre_scores", "ids", "scores"))
def _beam_search(ctx, ins, attrs):
    """One beam step. Inputs (padded):
      pre_ids    [batch, beam] int   — tokens chosen last step
      pre_scores [batch, beam] float — accumulated log-probs
      scores     [batch, beam, vocab] — next-token log-probs
    Outputs: selected_ids [batch, beam], selected_scores [batch, beam],
    parent_idx [batch, beam] (beam index each new hypothesis came from).
    Finished beams (pre_ids == end_id) are frozen: they only extend with
    end_id at unchanged score."""
    pre_ids = ins["pre_ids"][0].astype(jnp.int32)
    pre_scores = ins["pre_scores"][0]
    scores = ins["scores"][0]
    beam_size = attrs.get("beam_size", pre_ids.shape[1])
    end_id = attrs.get("end_id", 0)
    batch, beam, vocab = scores.shape

    finished = pre_ids == end_id  # [batch, beam]
    # is_accumulated (layer default True): `scores` already contain the
    # hypothesis history, so adding pre_scores would double-count; the raw
    # op default (False) matches the step form used by the op tests
    if attrs.get("is_accumulated", False):
        cont = scores  # [batch, beam, vocab]
    else:
        cont = pre_scores[:, :, None] + scores
    neg_inf = jnp.asarray(-1e9, scores.dtype)
    frozen = jnp.full_like(cont, neg_inf)
    frozen = frozen.at[:, :, end_id].set(pre_scores)
    total = jnp.where(finished[:, :, None], frozen, cont)

    flat = total.reshape(batch, beam * vocab)
    top_scores, top_idx = jax.lax.top_k(flat, beam_size)
    parent = top_idx // vocab
    token = top_idx % vocab
    return {
        "selected_ids": [token.astype(jnp.int32)],
        "selected_scores": [top_scores],
        "parent_idx": [parent.astype(jnp.int32)],
    }


@register(
    "beam_search_decode",
    no_grad_inputs=("Ids", "Scores", "ParentIdx", "SequenceLength"),
)
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack stored steps into full hypotheses.
    Inputs: Ids [T, batch, beam], ParentIdx [T, batch, beam],
    Scores [T, batch, beam]. Outputs SentenceIds [batch, beam, T] (padded
    with end_id) and SentenceScores [batch, beam] (final accumulated)."""
    ids = ins["Ids"][0].astype(jnp.int32)  # [T, B, K]
    if ins.get("ParentIdx"):
        parents = ins["ParentIdx"][0].astype(jnp.int32)
    else:
        # no backpointers recorded: beams never re-ordered (greedy decode)
        parents = jnp.broadcast_to(
            jnp.arange(ids.shape[2], dtype=jnp.int32)[None, None, :], ids.shape
        )
    scores = ins["Scores"][0]
    t, b, k = ids.shape
    end_id = attrs.get("end_id", 0)

    # start from the final beam order (identity), walk backwards
    def back(beam_idx, inp):
        ids_t, par_t = inp  # [B, K] each
        tok = jnp.take_along_axis(ids_t, beam_idx, axis=1)
        beam_prev = jnp.take_along_axis(par_t, beam_idx, axis=1)
        return beam_prev, tok

    init = jnp.broadcast_to(jnp.arange(k)[None, :], (b, k))
    _, toks_rev = jax.lax.scan(back, init, (jnp.flip(ids, 0), jnp.flip(parents, 0)))
    sent = jnp.flip(jnp.transpose(toks_rev, (1, 2, 0)), axis=2)  # [B, K, T]
    final_scores = scores[-1]  # [B, K]
    return {
        "SentenceIds": [sent],
        "SentenceScores": [final_scores],
    }
