"""Op lowering registry population.

Importing this package registers every op's JAX lowering rule (the analog of
the reference's static-initializer REGISTER_OPERATOR/REGISTER_OP_*_KERNEL
sites, op_registry.h).
"""

from ..core import registry
from . import common  # noqa: F401
from . import math_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import loss_ops  # noqa: F401
from . import image_ops  # noqa: F401
from . import crf_ops  # noqa: F401
from . import ctc_ops  # noqa: F401
from . import search_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import dist_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import control_ops  # noqa: F401
from . import compat_ops  # noqa: F401
from . import pallas_kernels  # noqa: F401

get_op = registry.get_op
is_registered = registry.is_registered
register = registry.register
