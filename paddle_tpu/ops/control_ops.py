"""Control-flow / tensor-array / recurrent op lowerings.

TPU-native re-design of the reference's dynamic-RNN machinery:

- ``recurrent`` — the engine behind StaticRNN/DynamicRNN
  (operators/recurrent_op.cc + controlflow/while_op.cc:36 + StepScopes).
  The reference interprets the step sub-block once per timestep in a fresh
  scope; here the sub-block is traced ONCE and wrapped in ``lax.scan``, so
  the whole recurrence is a single fused XLA loop and — unlike
  ``lax.while_loop`` — is reverse-differentiable.  DynamicRNN's ragged
  semantics (per-sequence lengths) become hold-state/zero-output masking
  against a ``SeqLen`` vector instead of the reference's rank-table
  batch-shrinking (lod_rank_table + shrink_rnn_memory), which XLA's static
  shapes cannot express.

- ``bounded_while`` — a gradient-capable While: a masked ``lax.scan`` over a
  static trip-count bound, where iterations after the condition goes false
  become no-ops (carry passthrough).  The unbounded forward-only ``while``
  lowering (core/trace.py -> lax.while_loop) remains for inference loops.

- tensor arrays (framework.proto LOD_TENSOR_ARRAY,
  controlflow/tensor_array_read_write_op.cc) — a ``TensorArray`` pytree of
  (stacked data, length) with static capacity, so arrays can be
  loop-carried through XLA control flow.

- ``switch`` — first-true-wins case selection (control_flow.py:1286): every
  case sub-block is traced (they are pure), results merged with
  ``jnp.where`` chains; the dominant use is piecewise lr schedules.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


# ---------------------------------------------------------------------------
# TensorArray value (LOD_TENSOR_ARRAY analog): static-capacity stacked store
# ---------------------------------------------------------------------------
class TensorArray:
    """(data [capacity, *elem], length int32) pytree so arrays can be
    loop-carried through lax.while_loop / lax.scan."""

    def __init__(self, data, length):
        self.data = data
        self.length = length

    def tree_flatten(self):
        return (self.data, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return "TensorArray(cap=%s, elem=%s)" % (
            self.data.shape[0], self.data.shape[1:])


jax.tree_util.register_pytree_node(
    TensorArray,
    lambda a: a.tree_flatten(),
    TensorArray.tree_unflatten,
)

_ARRAY_SLOTS = ("Array", "X", "Out")  # slots that may carry TensorArray values


def _scalar_i(i):
    return jnp.reshape(jnp.asarray(i), ()).astype(jnp.int32)


@register("write_to_array", no_grad_inputs=("I", "Array"))
def _write_to_array(ctx, ins, attrs):
    """tensor_array_read_write_op.cc WriteToArray: out[i] = x.  First write
    allocates a static-capacity store (attr `capacity`); the reference grows
    the vector dynamically, which XLA cannot."""
    x = ins["X"][0]
    i = _scalar_i(ins["I"][0])
    arr = ins["Array"][0] if ins.get("Array") else None
    if arr is None:
        cap = int(attrs.get("capacity", 128))
        data = jnp.zeros((cap,) + tuple(x.shape), x.dtype)
        length = jnp.int32(0)
    else:
        data, length = arr.data, arr.length
    data = jax.lax.dynamic_update_index_in_dim(
        data, x.astype(data.dtype), i, 0
    )
    return {"Out": [TensorArray(data, jnp.maximum(length, i + 1))]}


@register("read_from_array", no_grad_inputs=("X", "I"))
def _read_from_array(ctx, ins, attrs):
    arr = ins["X"][0]
    i = _scalar_i(ins["I"][0])
    out = jax.lax.dynamic_index_in_dim(arr.data, i, 0, keepdims=False)
    return {"Out": [out]}


@register("lod_array_length", no_grad_inputs=("X",))
def _lod_array_length(ctx, ins, attrs):
    return {"Out": [jnp.reshape(ins["X"][0].length, (1,)).astype(jnp.int32)]}


@register("lod_tensor_to_array", no_grad_inputs=("RankTable",))
def _lod_tensor_to_array(ctx, ins, attrs):
    """control_flow.py:825 / lod_tensor_to_array_op.cc: ragged batch ->
    per-timestep array.  Reference semantics: bucket by rank table (batch
    shrinks as short sequences end).  Padded re-expression: time-major
    stack (array[t] = full [B, ...] slice); consumers mask with SeqLen."""
    x = ins["X"][0]  # [B, T, ...]
    data = jnp.moveaxis(x, 1, 0)  # [T, B, ...]
    return {"Out": [TensorArray(data, jnp.int32(x.shape[1]))]}


@register("array_to_lod_tensor", no_grad_inputs=("RankTable",))
def _array_to_lod_tensor(ctx, ins, attrs):
    arr = ins["X"][0]
    return {"Out": [jnp.moveaxis(arr.data, 0, 1)]}  # [B, cap, ...]


@register("lod_rank_table", no_grad_inputs=("X", "SeqLen"))
def _lod_rank_table(ctx, ins, attrs):
    """control_flow.py:741: the rank table's payload on TPU is just the
    per-sequence length vector (sorting by length is a GPU batch-shrinking
    trick the padded representation doesn't need)."""
    if ins.get("SeqLen"):
        lens = ins["SeqLen"][0]
    else:
        x = ins["X"][0]
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return {"Out": [lens.astype(jnp.int32)]}


@register("max_sequence_len", no_grad_inputs=("RankTable",))
def _max_sequence_len(ctx, ins, attrs):
    return {"Out": [jnp.reshape(jnp.max(ins["RankTable"][0]), (1,)).astype(jnp.int32)]}


@register("shrink_rnn_memory", no_grad_inputs=("I", "RankTable"))
def _shrink_rnn_memory(ctx, ins, attrs):
    """control_flow.py:1111 / shrink_memory_op: the reference drops rows of
    finished sequences at step I.  Static-shape re-expression: zero-mask
    those rows (differentiable; downstream ops see zeros instead of absent
    rows)."""
    x = ins["X"][0]
    i = _scalar_i(ins["I"][0])
    lens = ins["RankTable"][0]
    active = (i < lens).astype(x.dtype)
    return {"Out": [x * active.reshape((-1,) + (1,) * (x.ndim - 1))]}


@register("rnn_memory_helper")
def _rnn_memory_helper(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


# ---------------------------------------------------------------------------
# recurrent: the StaticRNN / DynamicRNN engine
# ---------------------------------------------------------------------------
def _bcast_mask(mask, ref):
    """[B] bool -> broadcastable to ref (batch-leading)."""
    return mask.reshape(mask.shape + (1,) * (ref.ndim - mask.ndim))


@register("recurrent", no_grad_inputs=("SeqLen",))
def _recurrent(ctx, ins, attrs):
    """One lax.scan over the step sub-block (recurrent_op.cc analog).

    attrs:
      sub_block_idx     step block
      x_names           in-block names bound to per-step slices of X
      pre_state_names   in-block names bound to the carried state
      state_names       in-block names holding the updated state
      out_names         in-block names collected per step
      static_names      in-block aliases of whole (non-sliced) inputs
      ext_names         outer vars the sub-block reads (weights etc.)
      time_major        True: X/Out are [T, ...] (StaticRNN layout);
                        False: [B, T, ...] (DynamicRNN padded layout)
      is_reverse        scan the sequence right-to-left
    With SeqLen (DynamicRNN), finished sequences hold their state and emit
    zero outputs — the masking analog of shrink_rnn_memory.
    """
    xs = list(ins.get("X", []))
    inits = list(ins.get("InitState", []))
    statics = list(ins.get("Static", []))
    exts = list(ins.get("Ext", []))
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    time_major = bool(attrs.get("time_major", True))
    reverse = bool(attrs.get("is_reverse", False))
    sub = attrs["sub_block_idx"]

    xs_t = [x if time_major else jnp.moveaxis(x, 0, 1) for x in xs]  # [T,...]
    if xs_t:
        T = xs_t[0].shape[0]
    else:
        T = int(attrs["max_len"])

    base = {}
    base.update(zip(attrs.get("ext_names", []), exts))
    base.update(zip(attrs.get("static_names", []), statics))
    x_names = list(attrs.get("x_names", []))
    pre_names = list(attrs.get("pre_state_names", []))
    state_names = list(attrs.get("state_names", []))
    out_names = list(attrs.get("out_names", []))

    steps = jnp.arange(T, dtype=jnp.int32)
    if reverse:
        steps = steps[::-1]
        xs_t = [jnp.flip(x, 0) for x in xs_t]

    def body(carry, sl):
        t, xsl = sl
        env = dict(base)
        env.update(zip(x_names, xsl))
        env.update(zip(pre_names, carry))
        env = ctx.trace_block(sub, env)
        new = [env[n] for n in state_names]
        outs = [env[n] for n in out_names]
        if seq_len is not None:
            act = t < seq_len  # [B]
            new = [
                jnp.where(_bcast_mask(act, n_), n_, o_)
                for n_, o_ in zip(new, carry)
            ]
            outs = [
                jnp.where(_bcast_mask(act, o_), o_, jnp.zeros_like(o_))
                for o_ in outs
            ]
        return tuple(new), tuple(outs)

    carry, ys = jax.lax.scan(body, tuple(inits), (steps, tuple(xs_t)))
    ys = list(ys)
    if reverse:
        ys = [jnp.flip(y, 0) for y in ys]
    outs = [y if time_major else jnp.moveaxis(y, 0, 1) for y in ys]
    return {"Out": outs, "LastState": list(carry)}


# ---------------------------------------------------------------------------
# bounded_while: gradient-capable loop (masked scan over a static bound)
# ---------------------------------------------------------------------------
@register("bounded_while")
def _bounded_while(ctx, ins, attrs):
    """while_op.cc:36 with a static trip bound: scan `max_iters` times;
    once the condition var goes false the carry passes through unchanged.
    Reverse-differentiable (lax.while_loop is not), at the cost of always
    running max_iters steps — the classic TPU padding trade."""
    carried_names = list(attrs["carried_vars"])
    vals = list(ins["Carried"])
    base = dict(zip(attrs.get("ext_names", []), ins.get("Ext", [])))
    cond_idx = carried_names.index(attrs["cond_name"])
    sub = attrs["sub_block_idx"]

    def body(carry, _):
        active = jnp.reshape(carry[cond_idx], ()).astype(bool)
        env = dict(base)
        env.update(zip(carried_names, carry))
        env = ctx.trace_block(sub, env)
        new = [env[n] for n in carried_names]
        # tree_map so opaque carries (TensorArray pytrees) merge leaf-wise
        merged = tuple(
            jax.tree_util.tree_map(
                lambda a, b: jnp.where(active, a, b), n_, o_
            )
            for n_, o_ in zip(new, carry)
        )
        return merged, None

    max_iters = int(attrs["max_iters"])
    out, _ = jax.lax.scan(body, tuple(vals), None, length=max_iters)
    # surface silent truncation: the loop was supposed to run to cond=False
    final_cond = jnp.reshape(out[cond_idx], ()).astype(bool)
    jax.lax.cond(
        final_cond,
        lambda: jax.debug.print(
            "WARNING: bounded_while exhausted max_iters={m} with the "
            "condition still true — results are mid-loop state",
            m=max_iters,
        ),
        lambda: None,
    )
    return {"Out": list(out)}


# ---------------------------------------------------------------------------
# ifelse_select: row-wise branch merge (IfElse re-expression)
# ---------------------------------------------------------------------------
@register("ifelse_select", no_grad_inputs=("Cond",))
def _ifelse_select(ctx, ins, attrs):
    """Merge per-row branch results: out[b] = cond[b] ? x[b] : y[b].
    The dense re-expression of IfElse's split/merge (control_flow.py:1412):
    both branches were computed on the full batch; select is free next to
    the saved gather/scatter."""
    c = ins["Cond"][0]
    x = ins["X"][0]
    y = ins["Y"][0]
    c = jnp.reshape(c, (c.shape[0],) + (1,) * (x.ndim - 1)).astype(bool)
    return {"Out": [jnp.where(c, x, y.astype(x.dtype))]}


# ---------------------------------------------------------------------------
# switch: first-true-wins case merge (piecewise lr schedules etc.)
# ---------------------------------------------------------------------------
@register("switch", no_grad_inputs=("Cond",))
def _switch(ctx, ins, attrs):
    """control_flow.py:1286: every case sub-block is traced (pure under
    functionalized scope), then merged last-to-first with jnp.where so the
    FIRST true condition wins; the default block (or the var's incoming
    value) supplies the fallthrough."""
    written = list(attrs["written_names"])
    conds = list(ins.get("Cond", []))
    base = dict(zip(attrs.get("ext_names", []), ins.get("Ext", [])))
    cur = dict(zip(attrs.get("cur_names", []), ins.get("Cur", [])))

    def run_block(bidx):
        env = dict(base)
        env.update(cur)
        env = ctx.trace_block(bidx, env)
        vals = []
        for n in written:
            if n in env:
                vals.append(env[n])
            elif n in cur:
                vals.append(cur[n])
            else:
                raise RuntimeError(
                    "switch: var %s not written by every case and has no "
                    "prior value" % n
                )
        return vals

    default_idx = int(attrs.get("default_block_idx", -1))
    if default_idx >= 0:
        vals = run_block(default_idx)
    else:
        missing = [n for n in written if n not in cur]
        if missing:
            raise RuntimeError(
                "switch without default: vars %s need a prior value" % missing
            )
        vals = [cur[n] for n in written]

    case_blocks = list(attrs["case_blocks"])
    for ci in range(len(case_blocks) - 1, -1, -1):
        cvals = run_block(case_blocks[ci])
        c = jnp.reshape(conds[ci], ()).astype(bool)
        vals = [jnp.where(c, cv, v) for cv, v in zip(cvals, vals)]
    return {"Out": vals}


@register("recompute")
def _recompute(ctx, ins, attrs):
    """Rematerialization scope: run a sub-block under jax.checkpoint so
    its internal activations are recomputed in the backward pass instead
    of saved — the jax.checkpoint FLOPs-for-HBM trade as an IR construct.
    (The reference era predates RecomputeOptimizer; this is the TPU-native
    form: one op, grads via the generic vjp of the checkpointed region.)

    attrs: sub_block_idx, in_names (sub-block names for the X inputs, in
    order — also __bound_names__ for the read analysis), out_names
    (sub-block names emitted as Out), optional policy (a
    jax.checkpoint_policies name, e.g. "dots_saveable" /
    "dots_with_no_batch_dims_saveable" — the remat transpiler's
    save-the-matmuls middle ground; default saves nothing)."""
    sub = attrs["sub_block_idx"]
    in_names = list(attrs["in_names"])
    out_names = list(attrs["out_names"])
    vals = list(ins["X"])

    policy = None
    pname = attrs.get("policy")
    if pname:
        import jax.ad_checkpoint as adck

        policy = getattr(adck.checkpoint_policies, str(pname), None)
        if policy is None:
            raise ValueError(
                "recompute op: unknown jax.checkpoint policy %r (see "
                "jax.ad_checkpoint.checkpoint_policies)" % (pname,))

    def run(*args):
        env = dict(zip(in_names, args))
        env = ctx.trace_block(sub, env)
        return tuple(env[n] for n in out_names)

    run = (jax.checkpoint(run, policy=policy) if policy is not None
           else jax.checkpoint(run))
    outs = run(*vals)
    return {"Out": list(outs)}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py): the propagation engine walks
# sub-block-owning ops itself (while/cond/recompute recursion); the
# registrations here pin the slot schemas
# ---------------------------------------------------------------------------
from ..analysis.infer import register_infer  # noqa: E402

register_infer("recompute", req_ins=("X",))(None)
