"""Image / spatial op lowerings — the reference's misc vision op surface
(operators/affine_channel_op.cc, affine_grid_op.cc, crop_op.cc,
pad_constant_like_op.cc, multiplex_op.cc, space_to_depth_op.cc,
pool_with_index (pool_with_index_op.cc), unpool_op.cc, spp_op.cc,
pool3d (pool_op.cc), random_crop_op.cc, row_conv_op.cc, conv_shift_op.cc,
mean_iou_op.cc, is_empty_op.cc, shuffle_channel, anchor-free misc).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register


@register("affine_channel")
def _affine_channel(ctx, ins, attrs):
    x, scale, bias = ins["X"][0], ins["Scale"][0], ins["Bias"][0]
    layout = attrs.get("data_layout", "NCHW")
    shp = [1, -1, 1, 1] if layout == "NCHW" else [1, 1, 1, -1]
    return {"Out": [x * scale.reshape(shp) + bias.reshape(shp)]}


@register("affine_grid")
def _affine_grid(ctx, ins, attrs):
    # theta [N, 2, 3] -> sampling grid [N, H, W, 2] in [-1, 1] coords
    theta = ins["Theta"][0]
    if ins.get("OutputShape"):
        raise NotImplementedError("dynamic output_shape not supported; pass attr")
    n, c, h, w = attrs["output_shape"]
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    grid = jnp.einsum("hwk,njk->nhwj", base, theta)  # [N, H, W, 2]
    return {"Output": [grid.astype(theta.dtype)]}


@register("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    # bilinear sample x[N,C,H,W] at grid[N,Hg,Wg,2] (normalized [-1,1])
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0  # [N, Hg, Wg]
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yy, xx):
        valid = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        # batch gather: x[n, :, yc[n], xc[n]]
        out = jax.vmap(lambda img, yi, xi: img[:, yi, xi])(x, yc, xc)  # [N,C,Hg,Wg]
        return out * valid[:, None].astype(x.dtype)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    out = (
        v00 * (1 - wx_) * (1 - wy_)
        + v01 * wx_ * (1 - wy_)
        + v10 * (1 - wx_) * wy_
        + v11 * wx_ * wy_
    )
    return {"Output": [out]}


@register("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    if ins.get("Y") is not None and ins.get("Y"):
        shape = ins["Y"][0].shape
    if ins.get("Offsets"):
        raise NotImplementedError("tensor offsets unsupported (use attr)")
    return {
        "Out": [
            jax.lax.dynamic_slice(x, [int(o) for o in offsets], [int(s) for s in shape])
        ]
    }


@register("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    val = attrs.get("pad_value", 0.0)
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads, constant_values=val)]}


@register("multiplex", no_grad_inputs=("Ids",))
def _multiplex(ctx, ins, attrs):
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(ins["X"], axis=0)  # [K, B, ...]
    rows = jnp.arange(stacked.shape[1])
    return {"Out": [stacked[ids, rows]]}


@register("space_to_depth")
def _space_to_depth(ctx, ins, attrs):
    x = ins["X"][0]
    bs = attrs.get("blocksize", 2)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return {"Out": [x.reshape(n, c * bs * bs, h // bs, w // bs)]}


@register("shuffle_channel")
def _shuffle_channel(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    return {
        "Out": [
            jnp.transpose(x.reshape(n, g, c // g, h, w), (0, 2, 1, 3, 4)).reshape(
                x.shape
            )
        ]
    }


@register("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    """Max pool that also returns the flat h*w index of each max — the
    pool_with_index_op.cc contract consumed by unpool."""
    x = ins["X"][0]
    k = attrs.get("ksize", [2, 2])
    s = attrs.get("strides", k)
    p = attrs.get("paddings", [0, 0])
    n, c, h, w = x.shape
    if attrs.get("global_pooling", False):
        k = [h, w]
        p = [0, 0]
    # index grid of flat positions
    idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    idx = jnp.broadcast_to(idx, x.shape)
    window = (1, 1, k[0], k[1])
    strides = (1, 1, s[0], s[1])
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    # argmax via reduce_window over (value, index) pairs
    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_a = av >= bv
        return jnp.where(take_a, av, bv), jnp.where(take_a, ai, bi)

    out, oidx = jax.lax.reduce_window(
        (x, idx),
        (-jnp.inf, jnp.float32(-1)),
        sel,
        window,
        strides,
        pads,
    )
    return {"Out": [out], "Mask": [oidx.astype(jnp.int32)]}


@register("unpool", no_grad_inputs=("Indices",))
def _unpool(ctx, ins, attrs):
    # scatter pooled values back to the argmax positions (unpool_op.cc)
    x, indices = ins["X"][0], ins["Indices"][0]
    n, c, h, w = x.shape
    oh, ow = attrs.get("unpooled_size", [h * 2, w * 2])
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx = indices.reshape(n, c, h * w).astype(jnp.int32)
    vals = x.reshape(n, c, h * w)
    out = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].set(v)))(flat, idx, vals)
    return {"Out": [out.reshape(n, c, oh, ow)]}


@register("spp")
def _spp(ctx, ins, attrs):
    """Spatial pyramid pooling (spp_op.cc): concat of pyramid_height
    adaptive pools, flattened."""
    x = ins["X"][0]
    levels = attrs.get("pyramid_height", 3)
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lv in range(levels):
        bins = 2**lv
        kh, kw = int(np.ceil(h / bins)), int(np.ceil(w / bins))
        ph = kh * bins - h
        pw = kw * bins - w
        xp = jnp.pad(
            x,
            ((0, 0), (0, 0), (0, ph), (0, pw)),
            constant_values=-np.inf if ptype == "max" else 0.0,
        )
        xr = xp.reshape(n, c, bins, kh, bins, kw)
        if ptype == "max":
            pooled = jnp.max(xr, axis=(3, 5))
        else:
            pooled = jnp.sum(xr, axis=(3, 5)) / (kh * kw)
        outs.append(pooled.reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register("pool3d")
def _pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    k = attrs.get("ksize", [2, 2, 2])
    s = attrs.get("strides", k)
    p = attrs.get("paddings", [0, 0, 0])
    if attrs.get("global_pooling", False):
        axis = (2, 3, 4)
        out = jnp.max(x, axis=axis, keepdims=True) if ptype == "max" else jnp.mean(
            x, axis=axis, keepdims=True
        )
        return {"Out": [out]}
    window = (1, 1, k[0], k[1], k[2])
    strides = (1, 1, s[0], s[1], s[2])
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, pads)
    else:
        out = (
            jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
            / (k[0] * k[1] * k[2])
        )
    return {"Out": [out]}


@register("random_crop", needs_rng=True, no_grad_inputs=("Seed",))
def _random_crop(ctx, ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]  # crop shape for trailing dims
    lead = x.ndim - len(shape)
    key = ctx.rng(attrs)
    starts = []
    for i, s in enumerate(shape):
        key, sub = jax.random.split(key)
        hi = x.shape[lead + i] - s + 1
        starts.append(jax.random.randint(sub, (), 0, hi))
    begin = [0] * lead + starts
    sizes = list(x.shape[:lead]) + list(shape)
    out = jax.lax.dynamic_slice(x, begin, sizes)
    return {"Out": [out], "SeedOut": [jnp.zeros((1,), jnp.int32)]}


@register("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (row_conv_op.cc), padded layout
    [B, T, D] with filter [future_context+1, D]:
    out[b,t,d] = sum_{j} x[b,t+j,d] * w[j,d]."""
    x, w = ins["X"][0], ins["Filter"][0]
    k = w.shape[0]
    b, t, d = x.shape
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for j in range(k):
        out = out + xp[:, j : j + t] * w[j][None, None, :]
    return {"Out": [out]}


@register("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """Circular convolution (conv_shift_op.cc): x [B, N], y [B, M] (M odd),
    out[b, i] = sum_j x[b, (i + j - M//2) mod N] * y[b, j]."""
    x, y = ins["X"][0], ins["Y"][0]
    n = x.shape[1]
    m = y.shape[1]
    half = m // 2
    outs = []
    for j in range(m):
        outs.append(jnp.roll(x, half - j, axis=1) * y[:, j : j + 1])
    return {"Out": [sum(outs)]}


@register("mean_iou", no_grad_inputs=("Predictions", "Labels", "InWrongs", "InCorrects", "InMeanIou"))
def _mean_iou(ctx, ins, attrs):
    """Streaming mean IoU (mean_iou_op.h): per-class correct = intersection,
    wrong = pred-area + label-area - 2*intersection (both sides of each
    mismatch), accumulated with the In* carries; IoU per class =
    correct / (wrong + correct)."""
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    nc = attrs["num_classes"]
    inter = jnp.zeros((nc,), jnp.float32).at[
        jnp.where(pred == label, pred, nc - 1)
    ].add(jnp.where(pred == label, 1.0, 0.0))
    area_p = jnp.zeros((nc,), jnp.float32).at[pred].add(1.0)
    area_l = jnp.zeros((nc,), jnp.float32).at[label].add(1.0)
    correct = inter
    wrong = area_p + area_l - 2.0 * inter
    for w in ins.get("InWrongs") or []:
        wrong = wrong + w.astype(jnp.float32)
    for c in ins.get("InCorrects") or []:
        correct = correct + c.astype(jnp.float32)
    union = wrong + correct
    valid = union > 0
    iou = jnp.where(valid, correct / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    for m in ins.get("InMeanIou") or []:
        miou = miou + m.reshape(())
    return {
        "OutMeanIou": [miou],
        "OutWrong": [wrong.astype(jnp.int32)],
        "OutCorrect": [correct.astype(jnp.int32)],
    }


@register("is_empty", no_grad_inputs=("X",))
def _is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.asarray(x.size == 0)]}


@register("selu")
def _selu(ctx, ins, attrs):
    x = ins["X"][0]
    scale = attrs.get("scale", 1.0507009873554804934193349852946)
    alpha = attrs.get("alpha", 1.6732632423543772848170429916717)
    return {"Out": [scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))]}


@register("similarity_focus", no_grad_inputs=("X",))
def _similarity_focus(ctx, ins, attrs):
    # for each selected channel (axis=1 index), mark the max positions per
    # row/col of the HxW map (similarity_focus_op.cc, simplified contract:
    # output mask has 1 where the channel's value is a row-or-col max)
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    idx = attrs.get("indexes", [0])
    assert axis == 1, "similarity_focus supports channel axis only"
    masks = jnp.zeros_like(x)
    for ci in idx:
        ch = x[:, ci]  # [N, H, W]
        row_max = ch == jnp.max(ch, axis=2, keepdims=True)
        col_max = ch == jnp.max(ch, axis=1, keepdims=True)
        m = (row_max | col_max).astype(x.dtype)
        masks = masks + m[:, None] * jax.nn.one_hot(
            ci, x.shape[1], dtype=x.dtype
        ).reshape(1, -1, 1, 1)
    return {"Out": [jnp.clip(masks, 0.0, 1.0)]}


@register("add_position_encoding")
def _add_position_encoding(ctx, ins, attrs):
    """Sinusoidal position encoding add (add_position_encoding_op.cc):
    x [B, T, D]; out = alpha*x + beta*pos_enc."""
    x = ins["X"][0]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    half = d // 2
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos / div[None, :]
    parts = [jnp.sin(ang), jnp.cos(ang)]
    if d % 2:  # odd width: last column carries no encoding
        parts.append(jnp.zeros((t, 1), jnp.float32))
    enc = jnp.concatenate(parts, axis=1)  # [T, D]
    return {"Out": [alpha * x + beta * enc[None].astype(x.dtype)]}
