"""Reference-contract compatibility lowerings: the remaining
REGISTER_OPERATOR surface (SURVEY §2.6) expressed against the padded/
static-shape representation.

Each op cites its reference file.  Several delegate to an existing
TPU-native lowering (one implementation, reference-named entry points):
the reference's fused CPU-JIT kernels (fusion_gru/fusion_lstm,
fused_elemwise_activation) are real ops here but their fusion value is
provided by XLA, not hand-scheduling.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import get_op, register
from .common import jdt, stable_compact


def _delegate(type_, ctx, ins, attrs):
    return get_op(type_).lower(ctx, ins, attrs)


def project_input_maybe(ins):
    """fusion_gru/fusion_lstm/fused_embedding_fc_lstm shared in-op fc:
    with WeightX present, Input is the raw [B, T, D] sequence and the
    projection x @ WeightX (+ BiasX) happens inside the fused op."""
    if not ins.get("WeightX"):
        return ins
    xproj = ins["Input"][0] @ ins["WeightX"][0]
    if ins.get("BiasX"):
        xproj = xproj + ins["BiasX"][0].reshape(1, 1, -1)
    return dict(ins, Input=[xproj])


# ---------------------------------------------------------------------------
# full-sequence recurrent ops (gru_op.cc, lstm_op.cc, lstmp_op.cc,
# fused/fusion_gru_op.cc, fused/fusion_lstm_op.cc)
# ---------------------------------------------------------------------------
@register("gru")
@register("fusion_gru")
def _gru(ctx, ins, attrs):
    """gru_op.cc contract on the padded representation: Input is the
    projected gates [B, T, 3H]; emits Hidden (+ LastH).  The reference's
    LoD sequence2batch reordering has no analog — the time axis is
    explicit.  fusion_gru form (fused/fusion_gru_op.cc, the
    fc_gru_fuse_pass target): when WeightX [D, 3H] is given, Input is the
    RAW [B, T, D] sequence and the fc projection happens inside the op."""
    ins = project_input_maybe(ins)
    out = _delegate("padded_gru", ctx, ins, attrs)
    return {"Hidden": out["Hidden"], "LastH": out.get("LastH", [])}


@register("lstm")
@register("fusion_lstm")
def _lstm(ctx, ins, attrs):
    """lstm_op.cc contract: Input [B, T, 4H] projected gates -> Hidden and
    Cell, both [B, T, H] per-timestep sequences (the reference's
    BatchGate/BatchCellPreAct batch-reorder scratch outputs have no
    padded-representation analog).  fusion_lstm form
    (fused/fusion_lstm_op.cc, fc_lstm_fuse_pass target): with WeightX
    given, Input is the raw [B, T, D] sequence, projected in-op."""
    ins = project_input_maybe(ins)
    out = _delegate("padded_lstm", ctx, ins, attrs)
    return {
        "Hidden": out["Hidden"],
        "Cell": out["CellSeq"],
        "LastH": out["LastH"],
        "LastC": out["LastC"],
    }


@register("lstmp")
def _lstmp(ctx, ins, attrs):
    """lstmp_op.cc: LSTM with a recurrent projection layer — the hidden
    state fed back (and emitted) is h @ ProjWeight [H, P]."""
    xproj = ins["Input"][0]  # [B, T, 4H]
    w = ins["Weight"][0]  # [P, 4H] (recurrence consumes the projection)
    proj = ins["ProjWeight"][0]  # [H, P]
    b = ins["Bias"][0] if ins.get("Bias") else None
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    bsz, t, h4 = xproj.shape
    hid = h4 // 4
    p = proj.shape[1]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((bsz, p), xproj.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((bsz, hid), xproj.dtype)
    xs = jnp.swapaxes(xproj, 0, 1)
    steps = jnp.arange(t)

    def step(carry, inp):
        c_prev, r_prev = carry
        x_t, t_idx = inp
        gates = x_t + r_prev @ w
        if b is not None:
            gates = gates + b
        i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(c_hat)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        r = h @ proj
        if seq_len is not None:
            m = (t_idx < seq_len).astype(h.dtype)[:, None]
            c = m * c + (1 - m) * c_prev
            r = m * r + (1 - m) * r_prev
        return (c, r), (r, c)

    (c_fin, r_fin), (rs, cs) = jax.lax.scan(step, (c0, h0), (xs, steps))
    # Cell is the per-timestep cell sequence (lstmp_op.cc contract)
    return {"Projection": [jnp.swapaxes(rs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "LastC": [c_fin], "LastH": [r_fin]}


# ---------------------------------------------------------------------------
# sequence shape ops (sequence_ops/sequence_pad_op.cc, sequence_unpad_op.cc,
# sequence_reshape_op.cc, sequence_concat_op.cc)
# ---------------------------------------------------------------------------
@register("sequence_pad", no_grad_inputs=("PadValue", "SeqLen"))
def _sequence_pad(ctx, ins, attrs):
    """sequence_pad_op.cc: in the padded representation the data is already
    rectangular; this adjusts T to padded_length and fills tail slots with
    PadValue, emitting the per-row Length."""
    x = ins["X"][0]
    pad_value = ins["PadValue"][0].reshape(()) if ins.get("PadValue") else jnp.zeros((), x.dtype)
    seq_len = (
        ins["SeqLen"][0].reshape(-1).astype(jnp.int32)
        if ins.get("SeqLen")
        else jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    )
    t = x.shape[1]
    target = int(attrs.get("padded_length", -1))
    if target <= 0:
        target = t
    if target < t:
        # the reference errors when padded_length is below the longest
        # sequence; statically we only know the tensor bound, so reject
        # any configuration that could silently truncate valid steps
        raise ValueError(
            "sequence_pad: padded_length %d < time axis %d (would truncate "
            "valid data; the reference rejects this)" % (target, t)
        )
    if target > t:
        fill = jnp.full((x.shape[0], target - t) + x.shape[2:], pad_value, x.dtype)
        x = jnp.concatenate([x, fill], axis=1)
    mask = jnp.arange(x.shape[1], dtype=jnp.int32)[None, :] < seq_len[:, None]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    out = jnp.where(mask, x, pad_value.astype(x.dtype))
    return {"Out": [out], "Length": [jnp.minimum(seq_len, x.shape[1]).astype(jnp.int64)]}


@register("sequence_unpad", no_grad_inputs=("Length",))
def _sequence_unpad(ctx, ins, attrs):
    """sequence_unpad_op.cc: zero the slots past each row's Length (the
    ragged result stays padded — lengths are the LoD)."""
    x = ins["X"][0]
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    mask = jnp.arange(x.shape[1], dtype=jnp.int32)[None, :] < length[:, None]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(mask, x, 0)]}


@register("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    """sequence_reshape_op.cc: re-chunk each row's features: [B, T, D] ->
    [B, T*D/new_dim, new_dim]; lengths scale by D/new_dim."""
    x = ins["X"][0]
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    if (t * d) % new_dim != 0:
        raise ValueError(
            "sequence_reshape: %d elements per row not divisible by "
            "new_dim %d" % (t * d, new_dim)
        )
    if ins.get("SeqLen") and d % new_dim != 0:
        # with ragged rows a non-divisible feature dim would smear valid
        # elements across the padding boundary (the reference rejects
        # per-sequence non-divisible reshapes); dense full-length rows
        # have no boundary and stay allowed
        raise ValueError(
            "sequence_reshape: feature dim %d not divisible by new_dim %d "
            "with ragged rows (SeqLen present)" % (d, new_dim)
        )
    out = x.reshape(b, t * d // new_dim, new_dim)
    outs = {"Out": [out]}
    if ins.get("SeqLen"):
        lens = ins["SeqLen"][0].reshape(-1).astype(jnp.int64)
        outs["OutLen"] = [lens * d // new_dim]
    return outs


@register("sequence_concat", no_grad_inputs=("SeqLen",))
def _sequence_concat(ctx, ins, attrs):
    """sequence_ops/sequence_concat_op.cc: concatenate the i-th sequences
    of every input back to back.  Padded form: stitch each row's valid
    prefixes front-aligned into a [B, sum(T_i)] buffer."""
    xs = ins["X"]
    lens = ins.get("SeqLen")
    b = xs[0].shape[0]
    if lens:
        lens = [l.reshape(-1).astype(jnp.int32) for l in lens]
    else:
        lens = [jnp.full((b,), x.shape[1], jnp.int32) for x in xs]
    # big concat along time, then per-row stable compaction of valid slots
    data = jnp.concatenate(xs, axis=1)  # [B, total_t, ...]
    valid = jnp.concatenate(
        [
            jnp.arange(x.shape[1], dtype=jnp.int32)[None, :] < l[:, None]
            for x, l in zip(xs, lens)
        ],
        axis=1,
    )
    compacted, out_len = stable_compact(valid, data, axis=1)
    return {"Out": [compacted], "OutLen": [out_len.astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# lod plumbing (split_lod_tensor_op.cc, merge_lod_tensor_op.cc — the IfElse
# primitives — and reorder_lod_tensor_by_rank_op.cc, tensor_array_to_tensor)
# ---------------------------------------------------------------------------
@register("split_lod_tensor", no_grad_inputs=("Mask",))
def _split_lod_tensor(ctx, ins, attrs):
    """controlflow split: rows of X routed by boolean Mask into the true /
    false branches.  Static shapes: each branch is full-size with selected
    rows stably compacted to the front (+ counts), the TPU form of the
    reference's dynamic row split."""
    x = ins["X"][0]
    mask = ins["Mask"][0].reshape(-1).astype(bool)

    def take(cond):
        sel, cnt = stable_compact(cond, x, axis=0)
        return sel, cnt.astype(jnp.int64).reshape(1)

    out_true, cnt_t = take(mask)
    out_false, cnt_f = take(~mask)
    return {"OutTrue": [out_true], "OutFalse": [out_false],
            "CountTrue": [cnt_t], "CountFalse": [cnt_f]}


@register("merge_lod_tensor", no_grad_inputs=("Mask",))
def _merge_lod_tensor(ctx, ins, attrs):
    """controlflow merge: inverse of split_lod_tensor — front-compacted
    branch rows scattered back to their original positions by Mask."""
    mask = ins["Mask"][0].reshape(-1).astype(bool)
    in_true = ins["InTrue"][0]
    in_false = ins["InFalse"][0]
    n = mask.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # position of row i within its branch's compacted prefix
    pos_t = jnp.cumsum(mask.astype(jnp.int32)) - 1
    pos_f = jnp.cumsum((~mask).astype(jnp.int32)) - 1
    pick_t = jnp.take(in_true, jnp.clip(pos_t, 0, n - 1), axis=0)
    pick_f = jnp.take(in_false, jnp.clip(pos_f, 0, n - 1), axis=0)
    m = mask.reshape((n,) + (1,) * (in_true.ndim - 1))
    return {"Out": [jnp.where(m, pick_t, pick_f)]}


@register("reorder_lod_tensor_by_rank", no_grad_inputs=("RankTable",))
def _reorder_by_rank(ctx, ins, attrs):
    """reorder_lod_tensor_by_rank_op.cc: permute batch rows into the rank
    table's order (longest-first for RNN batching)."""
    x = ins["X"][0]
    perm = ins["RankTable"][0].reshape(-1).astype(jnp.int32)
    return {"Out": [jnp.take(x, perm, axis=0)]}


@register("tensor_array_to_tensor")
def _tensor_array_to_tensor(ctx, ins, attrs):
    """tensor_array_to_tensor_op.cc: stack/concat a TensorArray's written
    prefix along `axis`."""
    ta = ins["X"][0]  # TensorArray pytree (stacked data, length)
    if hasattr(ta, "data"):
        data, length = ta.data, ta.length
    else:
        data, length = ta, None
    if length is not None:
        # static capacity: unwritten slots are zeroed (the static-shape
        # analog of the reference's written-prefix-only concat)
        live = jnp.arange(data.shape[0], dtype=jnp.int32) < length
        data = jnp.where(
            live.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0
        )
    axis = int(attrs.get("axis", 0))
    use_stack = bool(attrs.get("use_stack", False))
    if use_stack:
        out = jnp.moveaxis(data, 0, axis)
    else:
        parts = [data[i] for i in range(data.shape[0])]
        out = jnp.concatenate(parts, axis=axis) if parts else data
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# misc delegates & small ops
# ---------------------------------------------------------------------------
@register("interpolate")
def _interpolate(ctx, ins, attrs):
    """interpolate_op.cc: dispatch on interp_method to the bilinear /
    nearest lowerings."""
    method = str(attrs.get("interp_method", "bilinear"))
    if method not in ("bilinear", "nearest"):
        raise NotImplementedError(
            "interpolate: interp_method %r (bilinear or nearest)" % method
        )
    return _delegate(
        "bilinear_interp" if method == "bilinear" else "nearest_interp",
        ctx, ins, attrs,
    )


@register("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx, ins, attrs):
    """conv_transpose_op.cc depthwise variant: groups == channels."""
    attrs = dict(attrs)
    attrs["groups"] = int(ins["Input"][0].shape[1])
    return _delegate("conv2d_transpose", ctx, ins, attrs)


@register("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, ins, attrs):
    """fused/fused_elemwise_activation_op.cc: functor_list like
    ["elementwise_add", "relu"] applied as f2(f1(x, y)).  XLA fuses this
    anyway; the op exists for program-level parity."""
    x, y = ins["X"][0], ins["Y"][0]
    functors = [str(f) for f in attrs.get("functor_list", [])]
    binary = {
        "elementwise_add": lambda a, b: a + b,
        "elementwise_sub": lambda a, b: a - b,
        "elementwise_mul": lambda a, b: a * b,
    }
    unary = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "scale": lambda a: a * float(attrs.get("scale", 1.0)),
    }
    if len(functors) != 2:
        raise NotImplementedError(
            "fused_elemwise_activation needs a 2-element functor_list, "
            "got %r" % (functors,)
        )
    f1, f2 = functors
    # the reference's two compound conventions:
    #   [binary, unary] -> Binary(X, Unary(Y))
    #   [unary, binary] -> Unary(Binary(X, Y))
    if f1 in binary and f2 in unary:
        out = binary[f1](x, unary[f2](y))
    elif f1 in unary and f2 in binary:
        out = unary[f1](binary[f2](x, y))
    else:
        raise NotImplementedError(
            "fused functor_list %r (need one binary + one unary of %s / %s)"
            % (functors, sorted(binary), sorted(unary))
        )
    return {"Out": [out]}


@register("fake_init", side_effect=False)
def _fake_init(ctx, ins, attrs):
    """distributed_ops/fake_init_op.cc: placeholder initializer for vars
    whose real values live on a pserver — zeros of the declared shape."""
    shape = [int(s) for s in attrs.get("shape", [1])]
    return {"Out": [jnp.zeros(shape, jdt(attrs.get("dtype", "float32")))]}


@register("lookup_sparse_table", no_grad_inputs=("Ids",))
def _lookup_sparse_table(ctx, ins, attrs):
    """lookup_sparse_table_op.cc: the auto-growth host table becomes a
    plain dense-table lookup on TPU (growth is a data-prep concern)."""
    return _delegate("lookup_table", ctx, ins, attrs)


@register(
    "split_ids", no_grad_inputs=("Ids",)
)
def _split_ids(ctx, ins, attrs):
    """distributed_ops/split_ids_op.cc: route ids to N shards by id % N.
    Static shapes: each shard output is full-size with its ids stably
    compacted to the front and a Count vector (the dynamic split of the
    reference re-expressed)."""
    ids = ins["Ids"][0].reshape(-1)
    n_shards = len(attrs.get("shard_names", [])) or int(attrs.get("num_shards", 2))
    outs, counts = [], []
    for s in range(n_shards):
        shard, cnt = stable_compact((ids % n_shards) == s, ids, axis=0)
        outs.append(shard)
        counts.append(cnt.astype(jnp.int64).reshape(1))
    return {"Out": outs, "Count": counts}


@register("merge_ids", no_grad_inputs=("Ids",))
def _merge_ids(ctx, ins, attrs):
    """distributed_ops/merge_ids_op.cc: gather per-shard row values back
    into the original id order (shard = id % N, row = position among that
    shard's ids in order)."""
    ids = ins["Ids"][0].reshape(-1)
    rows = ins["X"]  # per-shard value tensors [cap, D]
    n_shards = len(rows)
    n = ids.shape[0]
    shard = (ids % n_shards).astype(jnp.int32)
    # position of each id within its shard's compacted order
    pos = jnp.zeros((n,), jnp.int32)
    for s in range(n_shards):
        sel = shard == s
        pos = jnp.where(sel, jnp.cumsum(sel.astype(jnp.int32)) - 1, pos)
    stacked = jnp.stack([r for r in rows], axis=0)  # [S, cap, D]
    out = stacked[shard, jnp.clip(pos, 0, stacked.shape[1] - 1)]
    return {"Out": [out]}


# streaming detection-mAP accumulators (evaluator.DetectionMAP state):
# detection eval state is ragged per-class score lists — host state is
# the TPU-native seam, matching the op's host-callback design
_DETMAP_ACCUMS = {}
# keys whose accumulator was torn down by the OWNER's GC finalizer (not an
# explicit reset): a program that still runs the op afterwards is silently
# restarting its stream from empty — warn instead of hiding it
_DETMAP_FINALIZED = set()


def reset_detection_map_accum(key):
    """Clear the streaming accumulator behind an `accum_key` detection_map
    op (evaluator.DetectionMAP.reset) — an INTENTIONAL stream restart."""
    _DETMAP_ACCUMS.pop(key, None)
    _DETMAP_FINALIZED.discard(key)


def finalize_detection_map_accum(key):
    """GC-finalizer variant of reset: frees the accumulator AND remembers
    the key so a program that keeps running the op gets a warning when the
    stream silently restarts (ADVICE r5)."""
    _DETMAP_ACCUMS.pop(key, None)
    _DETMAP_FINALIZED.add(key)


def _detmap_feed(m, det_np, gt_np, evaluate_difficult):
    """One batch into a metrics.DetectionMAP: gt rows are [label, box]
    (width 5) or [label, difficult, box] (width 6, the reference's
    concat(gt_label, gt_difficult, gt_box) layout)."""
    gt_np = np.asarray(gt_np)
    if gt_np.ndim == 2 and gt_np.shape[1] == 6:
        labels, diff, boxes = gt_np[:, 0], gt_np[:, 1], gt_np[:, 2:6]
    else:
        labels, diff, boxes = gt_np[:, 0], None, gt_np[:, 1:5]
    m.update(np.asarray(det_np), boxes, labels,
             difficult=None if evaluate_difficult else diff)
    return np.float32(m.eval())


@register("detection_map", no_grad_inputs=("DetectRes", "Label"))
def _detection_map(ctx, ins, attrs):
    """detection/detection_map_op.cc: mAP via a host callback onto the
    same numpy evaluator that backs metrics.DetectionMAP (sorting/greedy
    matching is host work, not MXU work).  Without `accum_key`: the
    single-batch mAP (pure).  With `accum_key`: the STREAMING mAP — the
    callback owns a persistent accumulator under that key (the
    reference's Accum* state tensors re-homed host-side), sequenced with
    io_callback(ordered=True) so XLA can neither elide nor reorder the
    state update."""
    det = ins["DetectRes"][0]  # [N, 6] (label, score, x1, y1, x2, y2)
    gt = ins["Label"][0]  # [M, 5|6] (label[, difficult], x1, y1, x2, y2)
    overlap = float(attrs.get("overlap_threshold", 0.5))
    ap_version = str(attrs.get("ap_version", "integral"))
    ev_diff = bool(attrs.get("evaluate_difficult", True))
    accum_key = attrs.get("accum_key")

    from ..metrics import DetectionMAP

    if accum_key:
        raise ValueError(
            "detection_map with accum_key must be emitted as the "
            "side-effecting 'detection_map_accum' op type (DCE and the "
            "profiler's warm re-runs would corrupt the stream otherwise) "
            "— use layers.detection_map(accum_key=...)")

    def host_map(det_np, gt_np):
        m = DetectionMAP(overlap_threshold=overlap, ap_version=ap_version)
        return _detmap_feed(m, det_np, gt_np, ev_diff)

    out = jax.pure_callback(
        host_map, jax.ShapeDtypeStruct((), jnp.float32), det, gt
    )
    return {"MAP": [out.reshape(1)]}


@register("detection_map_accum", no_grad_inputs=("DetectRes", "Label"),
          side_effect=True)
def _detection_map_accum(ctx, ins, attrs):
    """STREAMING detection mAP (the accumulating detection_map variant):
    the host callback owns a persistent accumulator under `accum_key` —
    the reference's Accum* state tensors re-homed host-side.  A separate
    side-effecting op type so the executor's dead-op pruning never drops
    an unfetched accumulation and the profiler's warm re-runs never
    double-feed a batch; io_callback(ordered=True) stops XLA from
    eliding or reordering the update."""
    from jax.experimental import io_callback

    from ..metrics import DetectionMAP

    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    overlap = float(attrs.get("overlap_threshold", 0.5))
    ap_version = str(attrs.get("ap_version", "integral"))
    ev_diff = bool(attrs.get("evaluate_difficult", True))
    accum_key = str(attrs["accum_key"])

    def host_accum(det_np, gt_np):
        m = _DETMAP_ACCUMS.get(accum_key)
        if m is None:
            if accum_key in _DETMAP_FINALIZED:
                import warnings

                _DETMAP_FINALIZED.discard(accum_key)  # warn once per key
                warnings.warn(
                    "detection_map_accum %r: its DetectionMAP evaluator "
                    "was garbage-collected, so the streaming accumulator "
                    "restarts EMPTY mid-run — keep the evaluator (or the "
                    "program that owns it) alive for the accumulated mAP "
                    "to mean anything" % accum_key, RuntimeWarning)
            m = _DETMAP_ACCUMS[accum_key] = DetectionMAP(
                overlap_threshold=overlap, ap_version=ap_version)
        return _detmap_feed(m, det_np, gt_np, ev_diff)

    out = io_callback(
        host_accum, jax.ShapeDtypeStruct((), jnp.float32), det, gt,
        ordered=True,
    )
    return {"MAP": [out.reshape(1)]}
