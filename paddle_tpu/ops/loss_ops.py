"""Loss op lowerings — the long-tail loss surface of the reference
(operators/hinge_loss_op.cc, log_loss_op.cc, modified_huber_loss_op.cc,
rank_loss_op.cc, margin_rank_loss_op.cc, squared_l2_distance_op.cc,
cos_sim_op.cc, bilinear_tensor_product_op.cc, nce_op.cc,
hierarchical_sigmoid_op.cc, bpr_loss_op.cc).

All are pure elementwise/matmul compositions that XLA fuses; gradients come
from vjp of the lowering (no hand-written grad kernels needed).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register


@register("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    # loss = max(0, 1 - (2*label - 1) * logits)   (hinge_loss_op.cc)
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    y = 2.0 * labels - 1.0
    return {"Loss": [jnp.maximum(0.0, 1.0 - y * logits)]}


@register("log_loss")
def _log_loss(ctx, ins, attrs):
    # loss = -label*log(pred+eps) - (1-label)*log(1-pred+eps)
    pred, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(pred + eps) - (1.0 - label) * jnp.log(1.0 - pred + eps)
    return {"Loss": [loss]}


@register("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    # y' = 2y-1; z = y'*f;  z >= -1: max(0, 1-z)^2  else: -4z
    x, y = ins["X"][0], ins["Y"][0]
    yp = 2.0 * y - 1.0
    z = yp * x
    loss = jnp.where(z >= -1.0, jnp.square(jnp.maximum(0.0, 1.0 - z)), -4.0 * z)
    return {"IntermediateVal": [z], "Out": [loss]}


@register("rank_loss")
def _rank_loss(ctx, ins, attrs):
    # C = log(1 + exp(o_left - o_right)) - label * (o_left - o_right)
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jnp.logaddexp(0.0, d) - label * d]}


@register("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    # out = max(0, -label*(x1 - x2) + margin)
    label, x1, x2 = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    act = -label * (x1 - x2) + margin
    out = jnp.maximum(0.0, act)
    return {"Out": [out], "Activated": [(act > 0).astype(x1.dtype)]}


@register("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    # sub = x - y (y may have batch 1); out[i] = sum_j sub[i,j]^2
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    out = jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim))).reshape(-1, 1)
    return {"sub_result": [sub], "Out": [out]}


@register("cos_sim")
def _cos_sim(ctx, ins, attrs):
    # per-row cosine similarity; Y may have batch 1 (broadcast)
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    dot = jnp.sum(x * y, axis=1, keepdims=True)
    return {"Out": [dot / (xn * yn)], "XNorm": [xn], "YNorm": [yn]}


@register("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    # out[b, k] = x[b] @ W[k] @ y[b] + bias[k]
    x, w, y = ins["X"][0], ins["Weight"][0], ins["Y"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": [out]}


@register("bpr_loss")
def _bpr_loss(ctx, ins, attrs):
    # Bayesian personalized ranking: for each row, label picks the positive
    # logit; loss = mean over negatives of -log(sigmoid(pos - neg))
    x, label = ins["X"][0], ins["Label"][0]
    n, d = x.shape
    lab = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    diff = pos - x  # [n, d]; includes pos-pos = 0 term, excluded below
    logloss = -jax.nn.log_sigmoid(diff)
    mask = 1.0 - jax.nn.one_hot(lab, d, dtype=x.dtype)
    loss = jnp.sum(logloss * mask, axis=1, keepdims=True) / (d - 1)
    return {"Y": [loss]}


@register("kldiv_loss")
def _kldiv_loss(ctx, ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    reduction = attrs.get("reduction", "mean")
    loss = target * (jnp.where(target > 0, jnp.log(jnp.maximum(target, 1e-30)), 0.0) - x)
    if reduction == "mean":
        return {"Loss": [jnp.mean(loss)]}
    if reduction == "sum":
        return {"Loss": [jnp.sum(loss)]}
    if reduction == "batchmean":
        return {"Loss": [jnp.sum(loss) / x.shape[0]]}
    return {"Loss": [loss]}


# ---------------------------------------------------------------------------
# sampled-softmax family (nce_op.cc, hierarchical_sigmoid_op.cc)
# ---------------------------------------------------------------------------
@register("nce", no_grad_inputs=("Label", "SampleWeight", "CustomDistProbs"), needs_rng=True)
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation (nce_op.cc): binary-logistic loss on the
    true class vs `num_neg_samples` sampled noise classes.

    TPU design: negatives are sampled once per batch (shared negatives, the
    standard accelerator-friendly variant) with a uniform sampler, and all
    logits come from one [B, 1+S] gather+matmul — no per-sample loops.
    """
    x = ins["Input"][0]  # [B, D]
    w = ins["Weight"][0]  # [num_classes, D]
    label = ins["Label"][0].reshape(x.shape[0], -1)  # [B, num_true]
    num_classes = attrs["num_total_classes"]
    s = attrs.get("num_neg_samples", 10)
    num_true = label.shape[1]

    neg = jax.random.randint(ctx.rng(attrs), (s,), 0, num_classes)  # shared
    lab = label[:, 0].astype(jnp.int32)
    # logits for true + sampled classes
    w_true = w[lab]  # [B, D]
    w_neg = w[neg]  # [S, D]
    logit_true = jnp.sum(x * w_true, axis=1)  # [B]
    logit_neg = x @ w_neg.T  # [B, S]
    if ins.get("Bias"):
        b = ins["Bias"][0].reshape(-1)
        logit_true = logit_true + b[lab]
        logit_neg = logit_neg + b[neg][None, :]
    # P_noise uniform = 1/num_classes; nce logit corrections
    log_noise = jnp.log(jnp.asarray(s / float(num_classes), x.dtype))
    cost_true = -jax.nn.log_sigmoid(logit_true - log_noise)
    cost_neg = -jax.nn.log_sigmoid(-(logit_neg - log_noise))
    cost = cost_true + jnp.sum(cost_neg, axis=1)
    if ins.get("SampleWeight"):
        cost = cost * ins["SampleWeight"][0].reshape(-1)
    sample_logits = jnp.concatenate([logit_true[:, None], logit_neg], axis=1)
    sample_labels = jnp.concatenate(
        [lab[:, None], jnp.broadcast_to(neg[None, :], (x.shape[0], s))], axis=1
    )
    return {
        "Cost": [cost.reshape(-1, 1)],
        "SampleLogits": [sample_logits],
        "SampleLabels": [jax.lax.stop_gradient(sample_labels)],
    }


def _hsig_codes(num_classes, max_code_len):
    """Path codes/bits of a complete binary tree over `num_classes` leaves
    (the default coding of hierarchical_sigmoid_op.cc / matrix_bit_code.h):
    leaf i has code (i + num_classes) whose binary digits (below the MSB)
    give the left/right decisions; internal node index at each level is
    (code >> (len-1-d)) - 1 clipped to num_classes-1 rows of W."""
    codes = np.arange(num_classes) + num_classes
    lens = np.floor(np.log2(codes)).astype(np.int64)  # code length per leaf
    node_ids = np.zeros((num_classes, max_code_len), dtype=np.int64)
    bits = np.zeros((num_classes, max_code_len), dtype=np.float32)
    mask = np.zeros((num_classes, max_code_len), dtype=np.float32)
    for i in range(num_classes):
        c, l = int(codes[i]), int(lens[i])
        for d in range(l):
            node_ids[i, d] = (c >> (l - d)) - 1
            bits[i, d] = float((c >> (l - 1 - d)) & 1)
            mask[i, d] = 1.0
    return node_ids, bits, mask


@register("hierarchical_sigmoid", no_grad_inputs=("Label",))
def _hierarchical_sigmoid(ctx, ins, attrs):
    """Hierarchical sigmoid over a complete binary tree: O(log C) logistic
    decisions per sample, batched as a [B, L] gather+einsum."""
    x = ins["X"][0]  # [B, D]
    w = ins["W"][0]  # [num_classes - 1, D]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    num_classes = attrs["num_classes"]
    max_len = int(np.floor(np.log2(2 * num_classes - 1)))
    node_ids, bits, mask = _hsig_codes(num_classes, max_len)
    node_ids = jnp.asarray(node_ids)
    bits = jnp.asarray(bits, x.dtype)
    mask = jnp.asarray(mask, x.dtype)

    ids = node_ids[label]  # [B, L]
    bit = bits[label]
    m = mask[label]
    wsel = w[ids]  # [B, L, D]
    pre = jnp.einsum("bld,bd->bl", wsel, x)
    if ins.get("Bias"):
        pre = pre + ins["Bias"][0].reshape(-1)[ids]
    # label bit b: p = sigmoid(pre) if b==0 ... reference uses
    # sum log(1 + exp(pre)) - bit*pre over the path
    cost = jnp.sum((jnp.logaddexp(0.0, pre) - bit * pre) * m, axis=1)
    return {"Out": [cost.reshape(-1, 1)], "PreOut": [pre]}


@register("log_loss")
def _log_loss(ctx, ins, attrs):
    """operators/log_loss_op.cc: negative log likelihood of a probability."""
    p, y = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    out = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    return {"Loss": [out]}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py)
# ---------------------------------------------------------------------------
from ..analysis.infer import register_infer, same_as  # noqa: E402

register_infer("hinge_loss", req_ins=("Logits", "Labels"),
               req_outs=("Loss",))(same_as("Logits", out_slots=("Loss",)))
register_infer("log_loss", req_ins=("Predicted", "Labels"),
               req_outs=("Loss",))(same_as("Predicted", out_slots=("Loss",)))
register_infer("kldiv_loss", req_ins=("X", "Target"),
               req_outs=("Loss",))(None)  # shape depends on reduction attr
