"""Tensor creation / manipulation op lowerings.

Covers the reference's fill/rand init ops, reshape/transpose/concat/split/
slice family, cast, gather/scatter, lookup_table (embedding), one_hot, etc.
(various files under ``paddle/fluid/operators/``).  Random ops draw from the
trace RNG key via ``ctx.rng`` — the functional replacement for the
reference's per-op seed + global generator.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register
from .common import jdt


# ---------------------------------------------------------------------------
# creation ops
# ---------------------------------------------------------------------------
@register("fill_constant")
def _fill_constant(ctx, ins, attrs):
    shape = attrs.get("shape", [1])
    dtype = jdt(attrs.get("dtype", "float32"))
    value = attrs.get("value", 0.0)
    return {"Out": [jnp.full(tuple(int(s) for s in shape), value, dtype=dtype)]}


@register("fill_constant_batch_size_like")
def _fill_constant_bsl(ctx, ins, attrs):
    x = ins["Input"][0]
    shape = list(attrs.get("shape"))
    in_dim = attrs.get("input_dim_idx", 0)
    out_dim = attrs.get("output_dim_idx", 0)
    shape[out_dim] = x.shape[in_dim]
    dtype = jdt(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dtype)]}


@register("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.zeros_like(x)]}


@register("fill_any_like")
def _fill_any_like(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.full_like(x, attrs.get("value", 0.0))]}


@register("uniform_random", needs_rng=True)
def _uniform_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dtype = jdt(attrs.get("dtype", "float32"))
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    out = jax.random.uniform(ctx.rng(attrs), shape, dtype=jnp.float32, minval=lo, maxval=hi)
    return {"Out": [out.astype(dtype)]}


@register("gaussian_random", needs_rng=True)
def _gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dtype = jdt(attrs.get("dtype", "float32"))
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    out = jax.random.normal(ctx.rng(attrs), shape, dtype=jnp.float32) * std + mean
    return {"Out": [out.astype(dtype)]}


@register("truncated_gaussian_random", needs_rng=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dtype = jdt(attrs.get("dtype", "float32"))
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    out = jax.random.truncated_normal(ctx.rng(attrs), -2.0, 2.0, shape, jnp.float32)
    return {"Out": [(out * std + mean).astype(dtype)]}


@register("randint", needs_rng=True, no_grad_inputs=("X",))
def _randint(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    out = jax.random.randint(
        ctx.rng(attrs), shape, attrs.get("low", 0), attrs.get("high", 100)
    )
    return {"Out": [out.astype(jdt(attrs.get("dtype", "int64")))]}


@register("range", no_grad_inputs=("Start", "End", "Step"))
def _range(ctx, ins, attrs):
    # static variant: attrs carry values (layers.arange)
    start = attrs.get("start", 0)
    end = attrs.get("end")
    step = attrs.get("step", 1)
    dtype = jdt(attrs.get("dtype", "int64"))
    return {"Out": [jnp.arange(start, end, step, dtype=dtype)]}


@register("assign")
def _assign(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register("assign_value")
def _assign_value(ctx, ins, attrs):
    vals = np.array(attrs["values"], dtype=np.dtype(attrs.get("np_dtype", "float32")))
    shape = attrs.get("shape", None)
    if shape:
        vals = vals.reshape(shape)
    return {"Out": [jnp.asarray(vals, dtype=jdt(str(vals.dtype)))]}


@register("shape", no_grad_inputs=("Input",))
def _shape(ctx, ins, attrs):
    x = ins["Input"][0]
    return {"Out": [jnp.array(x.shape, dtype=jnp.int32)]}


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------
def _resolve_reshape(x, shape):
    shape = list(shape)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = int(np.prod(x.shape) // known)
    return tuple(shape)


@register("reshape")
@register("reshape2")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [x.reshape(_resolve_reshape(x, attrs["shape"]))]}


@register("transpose")
@register("transpose2")
def _transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


@register("flatten")
@register("flatten2")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": [x.reshape(lead, -1)]}


@register("squeeze")
@register("squeeze2")
def _squeeze(ctx, ins, attrs):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if not axes:
        return {"Out": [jnp.squeeze(x)]}
    return {"Out": [jnp.squeeze(x, axis=tuple(a % x.ndim for a in axes))]}


@register("unsqueeze")
@register("unsqueeze2")
def _unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": [x]}


@register("concat")
def _concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register("split_byref")
@register("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("stack")
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    outs = [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]
    return {"Y": outs}


@register("slice")
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes, starts, ends = attrs["axes"], attrs["starts"], attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    for a in sorted(attrs.get("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, a)
    return {"Out": [out]}


@register("strided_slice")
def _strided_slice(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"], attrs["strides"]):
        idx[a] = slice(s, e, st)
    return {"Out": [x[tuple(idx)]]}


@register("expand")
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register("expand_as")
def _expand_as(ctx, ins, attrs):
    x, y = ins["X"][0], ins["target_tensor"][0]
    reps = [t // s for s, t in zip(x.shape, y.shape)]
    return {"Out": [jnp.tile(x, reps)]}


@register("tile")
def _tile(ctx, ins, attrs):
    return {"Out": [jnp.tile(ins["X"][0], attrs["repeat_times"])]}


@register("cast")
def _cast(ctx, ins, attrs):
    out_dtype = jdt(attrs["out_dtype"])
    return {"Out": [ins["X"][0].astype(out_dtype)]}


@register("pad")
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    paddings = attrs["paddings"]
    pad_width = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return {
        "Out": [jnp.pad(x, pad_width, constant_values=attrs.get("pad_value", 0.0))]
    }


@register("pad2d")
def _pad2d(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    pw = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if attrs.get("data_format", "NCHW") == "NHWC":
        pw = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    kw = {"constant_values": attrs.get("pad_value", 0.0)} if mode == "constant" else {}
    return {"Out": [jnp.pad(x, pw, mode=jmode, **kw)]}


@register("reverse")
def _reverse(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.flip(x, axis=tuple(attrs["axis"]))]}


@register("roll")
def _roll(ctx, ins, attrs):
    return {"Out": [jnp.roll(ins["X"][0], attrs["shifts"], attrs.get("axis"))]}


# ---------------------------------------------------------------------------
# gather / scatter / embedding
# ---------------------------------------------------------------------------
@register("gather", no_grad_inputs=("Index",))
def _gather(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [jnp.take(x, idx.astype(jnp.int32), axis=attrs.get("axis", 0))]}


@register("gather_nd", no_grad_inputs=("Index",))
def _gather_nd(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0].astype(jnp.int32)
    return {"Out": [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


@register("scatter", no_grad_inputs=("Ids",))
def _scatter(ctx, ins, attrs):
    x, ids, updates = ins["X"][0], ins["Ids"][0].astype(jnp.int32), ins["Updates"][0]
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(updates)]}
    return {"Out": [x.at[ids].add(updates)]}


@register("lookup_table", no_grad_inputs=("Ids",))
@register("lookup_table_v2", no_grad_inputs=("Ids",))
def _lookup_table(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    ids = ids.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    out = jnp.take(w, ids, axis=0)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        mask = (ids != pad).astype(out.dtype)[..., None]
        out = out * mask
    return {"Out": [out]}


@register("lookup_table_grad", handles_selected_rows=True)
@register("lookup_table_v2_grad", handles_selected_rows=True)
def _lookup_table_grad(ctx, ins, attrs):
    """Sparse-aware embedding grad (lookup_table_op.cc grad kernel): with
    is_sparse the W gradient is emitted as SelectedRows (ids, rows) —
    never a [vocab, dim] dense tensor — exactly the reference's
    SELECTED_ROWS output var type (selected_rows.h:32).  Dense mode
    falls back to the generic vjp lowering."""
    from ..core.registry import lower_grad_op
    from ..core.selected_rows import SelectedRows

    fwd_attrs = attrs.get("__fwd_attrs__", {})
    if not fwd_attrs.get("is_sparse", False):
        return lower_grad_op(ctx, None, ins, attrs)

    w, ids, og = ins["W"][0], ins["Ids"][0], ins["Out@GRAD"][0]
    ids = ids.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    rows = ids.reshape(-1)
    vals = og.reshape(-1, og.shape[-1]).astype(w.dtype)
    pad = fwd_attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        vals = jnp.where((rows == pad)[:, None], 0.0, vals)
    return {"W@GRAD": [SelectedRows(rows, vals, w.shape[0])]}


@register("split_selected_rows", handles_selected_rows=True)
def _split_selected_rows(ctx, ins, attrs):
    """split_selected_rows_op.cc: route a SelectedRows' rows into
    height_sections buckets (the pserver param-shard scatter).  Static
    shapes: every output keeps the full row list, with rows outside its
    section remapped to the out-of-range sentinel (height), which every
    consumer drops; in-section rows are rebased to the section-local
    index, matching the reference's per-shard row numbering."""
    from ..core.selected_rows import SelectedRows

    x = ins["X"][0]
    sections = [int(s) for s in attrs.get("height_sections", [])]
    if not isinstance(x, SelectedRows):
        idx = np.cumsum(sections)[:-1].tolist()
        return {"Out": list(jnp.split(x, idx, axis=0))}
    outs = []
    offset = 0
    for h in sections:
        in_sec = (x.rows >= offset) & (x.rows < offset + h)
        rows = jnp.where(in_sec, x.rows - offset, h)
        vals = jnp.where(in_sec[:, None], x.value, 0)
        outs.append(SelectedRows(rows, vals, h))
        offset += h
    return {"Out": outs}


@register("one_hot", no_grad_inputs=("X",))
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0].astype(jnp.int32)
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return {"Out": [jax.nn.one_hot(x, attrs["depth"], dtype=jnp.float32)]}


@register("index_select", no_grad_inputs=("Index",))
def _index_select(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0].astype(jnp.int32)
    return {"Out": [jnp.take(x, idx, axis=attrs.get("dim", 0))]}


@register("where", no_grad_inputs=("Condition",))
def _where(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register("where_index", no_grad_inputs=("Condition",))
def _where_index(ctx, ins, attrs):
    # dynamic-size output: returns padded index list (size = numel)
    cond = ins["Condition"][0]
    idx = jnp.stack(jnp.nonzero(cond, size=cond.size, fill_value=-1), axis=-1)
    return {"Out": [idx.astype(jnp.int32)]}


@register("increment")
def _increment(ctx, ins, attrs):
    x = ins["X"][0]
    # increment_op.cc keeps the input dtype (int step counters stay int)
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0)).astype(x.dtype)]}


@register("print", no_grad_inputs=("In",), side_effect=True)
def _print(ctx, ins, attrs):
    x = ins["In"][0]
    jax.debug.print(attrs.get("message", "") + " {}", x)
    return {"Out": [x]}


@register("linspace")
def _linspace(ctx, ins, attrs):
    return {
        "Out": [
            jnp.linspace(
                attrs["start"], attrs["stop"], attrs["num"], dtype=jdt(attrs.get("dtype", "float32"))
            )
        ]
    }


@register("eye")
def _eye(ctx, ins, attrs):
    return {
        "Out": [
            jnp.eye(
                attrs["num_rows"],
                attrs.get("num_columns", None),
                dtype=jdt(attrs.get("dtype", "float32")),
            )
        ]
    }


@register("diag")
def _diag(ctx, ins, attrs):
    return {"Out": [jnp.diag(ins["Diagonal"][0])]}


@register("meshgrid")
def _meshgrid(ctx, ins, attrs):
    outs = jnp.meshgrid(*ins["X"], indexing="ij")
    return {"Out": list(outs)}


@register("gaussian_random_batch_size_like", needs_rng=True)
def _gaussian_random_bsl(ctx, ins, attrs):
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    out = jax.random.normal(ctx.rng(attrs), tuple(shape)) * attrs.get(
        "std", 1.0
    ) + attrs.get("mean", 0.0)
    return {"Out": [out.astype(jdt(attrs.get("dtype", "float32")))]}


@register("uniform_random_batch_size_like", needs_rng=True)
def _uniform_random_bsl(ctx, ins, attrs):
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    out = jax.random.uniform(
        ctx.rng(attrs),
        tuple(shape),
        minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0),
    )
    return {"Out": [out.astype(jdt(attrs.get("dtype", "float32")))]}


# ---------------------------------------------------------------------------
# static infer rules (analysis/infer.py)
# ---------------------------------------------------------------------------
from ..analysis.infer import (  # noqa: E402
    InferError,
    VarInfo,
    attr_dtype,
    numel_known,
    register_infer,
    same_as,
    slot_info as _i,
)


def _shape_attr_infer(op, ins):
    shape = tuple(int(s) for s in op.attrs.get("shape", [1]))
    return {"Out": [VarInfo(
        shape, attr_dtype(op.attrs.get("dtype"), "float32"))]}


register_infer("fill_constant", req_ins=())(_shape_attr_infer)
register_infer("uniform_random", req_ins=())(_shape_attr_infer)
register_infer("gaussian_random", req_ins=())(_shape_attr_infer)
register_infer("truncated_gaussian_random", req_ins=())(_shape_attr_infer)
register_infer("randint", req_ins=())(_shape_attr_infer)


@register_infer("assign_value", req_ins=())
def _assign_value_infer(op, ins):
    shape = op.attrs.get("shape", None)
    return {"Out": [VarInfo(
        tuple(int(s) for s in shape) if shape else None,
        attr_dtype(op.attrs.get("np_dtype"), "float32"))]}


register_infer("assign", req_ins=("X",))(same_as("X"))
register_infer("fill_zeros_like", req_ins=("X",))(same_as("X"))
register_infer("fill_any_like", req_ins=("X",))(same_as("X"))
register_infer("increment", req_ins=("X",))(same_as("X"))


@register_infer("shape", req_ins=("Input",))
def _shape_op_infer(op, ins):
    x = _i(ins, "Input")
    nd = None if x is None or x.shape is None else len(x.shape)
    return {"Out": [VarInfo((nd,) if nd is not None else None, "int32")]}


@register_infer("reshape", req_ins=("X",))
@register_infer("reshape2", req_ins=("X",))
def _reshape_infer(op, ins):
    x = _i(ins, "X")
    target = [int(s) for s in op.attrs["shape"]]
    xshape = None if x is None else x.shape
    out = []
    for i, s in enumerate(target):
        if s == 0:
            if xshape is None or i >= len(xshape):
                out.append(-1)
            else:
                out.append(xshape[i])
        else:
            out.append(s)
    if -1 in out:
        total = numel_known(xshape) if xshape is not None else None
        known = numel_known([d for d in out if d != -1])
        if total is not None and known:
            if out.count(-1) == 1 and total % known == 0:
                out[out.index(-1)] = total // known
    else:
        total = numel_known(xshape) if xshape is not None else None
        tgt = numel_known(out)
        if total is not None and tgt is not None and total != tgt:
            raise InferError(
                "reshape of %s (%d elements) to %s (%d elements)"
                % (xshape, total, tuple(out), tgt))
    return {"Out": [VarInfo(tuple(out), x.dtype if x else None)]}


@register_infer("transpose", req_ins=("X",))
@register_infer("transpose2", req_ins=("X",))
def _transpose_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    perm = [int(a) for a in op.attrs["axis"]]
    if sorted(perm) != list(range(len(x.shape))):
        raise InferError(
            "transpose axis %s is not a permutation of rank %d"
            % (perm, len(x.shape)))
    return {"Out": [VarInfo(tuple(x.shape[a] for a in perm), x.dtype)]}


@register_infer("squeeze", req_ins=("X",))
@register_infer("squeeze2", req_ins=("X",))
def _squeeze_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    axes = op.attrs.get("axes", [])
    if not axes:
        shape = tuple(d for d in x.shape if d != 1)
    else:
        drop = set(int(a) % len(x.shape) for a in axes)
        shape = tuple(d for i, d in enumerate(x.shape) if i not in drop)
    return {"Out": [VarInfo(shape, x.dtype)]}


@register_infer("unsqueeze", req_ins=("X",))
@register_infer("unsqueeze2", req_ins=("X",))
def _unsqueeze_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    shape = list(x.shape)
    for a in sorted(int(a) for a in op.attrs["axes"]):
        shape.insert(a if a >= 0 else a + len(shape) + 1, 1)
    return {"Out": [VarInfo(tuple(shape), x.dtype)]}


@register_infer("flatten", req_ins=("X",))
@register_infer("flatten2", req_ins=("X",))
def _flatten_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    axis = int(op.attrs.get("axis", 1))
    lead = numel_known(x.shape[:axis]) if axis > 0 else 1
    tail = numel_known(x.shape[axis:])
    return {"Out": [VarInfo(
        (lead if lead is not None else -1,
         tail if tail is not None else -1), x.dtype)]}


@register_infer("concat", req_ins=("X",))
def _concat_infer(op, ins):
    xs = [v for v in ins.get("X", []) if v is not None]
    if not xs or any(v.shape is None for v in xs):
        return {}
    nd = len(xs[0].shape)
    if any(len(v.shape) != nd for v in xs):
        raise InferError(
            "concat rank mismatch: %s" % [v.shape for v in xs])
    ax = int(op.attrs.get("axis", 0)) % nd
    shape = []
    for i in range(nd):
        if i == ax:
            dims = [v.shape[i] for v in xs]
            shape.append(-1 if any(d < 0 for d in dims) else sum(dims))
        else:
            dims = set(v.shape[i] for v in xs if v.shape[i] >= 0)
            if len(dims) > 1:
                raise InferError(
                    "concat non-axis dim %d mismatch: %s"
                    % (i, [v.shape for v in xs]))
            shape.append(dims.pop() if dims else -1)
    return {"Out": [VarInfo(tuple(shape), xs[0].dtype)]}


@register_infer("stack", req_ins=("X",), req_outs=("Y",))
def _stack_infer(op, ins):
    xs = [v for v in ins.get("X", []) if v is not None]
    if not xs or xs[0].shape is None:
        return {}
    ax = int(op.attrs.get("axis", 0))
    shape = list(xs[0].shape)
    shape.insert(ax if ax >= 0 else ax + len(shape) + 1, len(xs))
    return {"Y": [VarInfo(tuple(shape), xs[0].dtype)]}


@register_infer("slice", req_ins=("Input",))
def _slice_infer(op, ins):
    x = _i(ins, "Input")
    if x is None or x.shape is None:
        return {}
    shape = list(x.shape)
    for a, s, e in zip(op.attrs["axes"], op.attrs["starts"],
                       op.attrs["ends"]):
        a, s, e = int(a), int(s), int(e)
        dim = shape[a]
        if dim < 0:
            continue
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        shape[a] = max(e - s, 0)
    for a in sorted(
            (int(a) for a in op.attrs.get("decrease_axis", [])),
            reverse=True):
        del shape[a]
    return {"Out": [VarInfo(tuple(shape), x.dtype)]}


@register_infer("cast", req_ins=("X",))
def _cast_infer(op, ins):
    x = _i(ins, "X")
    return {"Out": [VarInfo(
        x.shape if x else None, attr_dtype(op.attrs.get("out_dtype")))]}


@register_infer("gather", req_ins=("X", "Index"))
def _gather_infer(op, ins):
    x, idx = _i(ins, "X"), _i(ins, "Index")
    if x is None or x.shape is None or idx is None or idx.shape is None:
        return {}
    ax = int(op.attrs.get("axis", 0)) % len(x.shape)
    shape = x.shape[:ax] + idx.shape + x.shape[ax + 1:]
    return {"Out": [VarInfo(shape, x.dtype)]}


@register_infer("lookup_table", req_ins=("W", "Ids"))
@register_infer("lookup_table_v2", req_ins=("W", "Ids"))
def _lookup_infer(op, ins):
    w, ids = _i(ins, "W"), _i(ins, "Ids")
    if w is None or w.shape is None or ids is None or ids.shape is None:
        return {}
    ishape = ids.shape
    if len(ishape) >= 2 and ishape[-1] == 1:
        ishape = ishape[:-1]
    return {"Out": [VarInfo(ishape + (w.shape[-1],), w.dtype)]}


@register_infer("one_hot", req_ins=("X",))
def _one_hot_infer(op, ins):
    x = _i(ins, "X")
    if x is None or x.shape is None:
        return {}
    shape = x.shape
    if len(shape) >= 2 and shape[-1] == 1:
        shape = shape[:-1]
    return {"Out": [VarInfo(shape + (int(op.attrs["depth"]),), "float32")]}


register_infer("expand", req_ins=("X",))(None)
register_infer("split", req_ins=("X",))(None)
register_infer("scatter", req_ins=("X", "Ids", "Updates"))(same_as("X"))
