"""In-graph metric ops (operators/metrics/auc_op.cc,
precision_recall_op.cc; accuracy lives in math_ops).

Stateful accumulators are expressed functionally: the running stat tensors
come in as inputs and go out as outputs, threaded through the scope by the
executor's state functionalization (the TPU analog of the reference's
in-place Variable updates).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


@register("auc", no_grad_inputs=("Predict", "Label", "StatPos", "StatNeg"))
def _auc(ctx, ins, attrs):
    """Streaming AUC over threshold buckets (auc_op.cc): histogram positive
    and negative scores into num_thresholds buckets, trapezoid-integrate.
    slide_steps=0 accumulates globally; slide_steps=S keeps a shift
    register of the last S batch histograms (auc_op.h statAuc) and the
    AUC is computed from the window sum — the reference's batch AUC."""
    predict = ins["Predict"][0]
    label = ins["Label"][0].reshape(-1)
    num_thresholds = attrs.get("num_thresholds", 4095)
    slide_steps = int(attrs.get("slide_steps", 0))
    curve = str(attrs.get("curve", "ROC")).upper()
    if curve not in ("ROC", "PR"):
        raise ValueError("auc: unsupported curve %r (ROC or PR)" % curve)
    if predict.ndim > 2 or (predict.ndim == 2 and predict.shape[1] > 2):
        raise ValueError(
            "auc: Predict must be [N] scores or [N, 2] binary probabilities, "
            "got %s" % (predict.shape,)
        )
    pos_score = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 else predict.reshape(-1)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    bucket = jnp.clip(
        (pos_score * num_thresholds).astype(jnp.int32), 0, num_thresholds
    )
    is_pos = (label > 0).astype(stat_pos.dtype)
    nb = num_thresholds + 1
    cur_pos = jnp.zeros((nb,), stat_pos.dtype).at[bucket].add(is_pos)
    cur_neg = jnp.zeros((nb,), stat_neg.dtype).at[bucket].add(1.0 - is_pos)
    if slide_steps > 0:
        # [S, nb] shift register: drop the oldest row, append this batch
        sp = stat_pos.reshape(slide_steps, nb)
        sn = stat_neg.reshape(slide_steps, nb)
        new_pos_state = jnp.concatenate([sp[1:], cur_pos[None]], axis=0)
        new_neg_state = jnp.concatenate([sn[1:], cur_neg[None]], axis=0)
        new_pos = jnp.sum(new_pos_state, axis=0)
        new_neg = jnp.sum(new_neg_state, axis=0)
    else:
        new_pos_state = new_pos = stat_pos.reshape(-1) + cur_pos
        new_neg_state = new_neg = stat_neg.reshape(-1) + cur_neg
    # trapezoid integration over buckets in descending-threshold order
    pos_flip = jnp.flip(new_pos)
    neg_flip = jnp.flip(new_neg)
    tp = jnp.cumsum(pos_flip)
    fp = jnp.cumsum(neg_flip)
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp_prev = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    if curve == "PR":
        # precision-recall area, right-endpoint (step) integration à la
        # average precision: sum over thresholds of d(recall) * precision.
        # (Trapezoids would need precision at tp_prev+fp_prev==0 and bias
        # the first bucket low for sharp classifiers.)
        recall = tp / jnp.maximum(tot_pos, 1.0)
        recall_prev = tp_prev / jnp.maximum(tot_pos, 1.0)
        precision = tp / jnp.maximum(tp + fp, 1.0)
        area = jnp.sum((recall - recall_prev) * precision)
        auc = jnp.where(tot_pos > 0, area, 0.0)
    else:
        area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
        auc = jnp.where(
            tot_pos * tot_neg > 0, area / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0
        )
    return {
        "AUC": [auc],
        "StatPosOut": [new_pos_state.reshape(ins["StatPos"][0].shape)],
        "StatNegOut": [new_neg_state.reshape(ins["StatNeg"][0].shape)],
    }


@register(
    "precision_recall",
    no_grad_inputs=("MaxProbs", "Indices", "Labels", "Weights", "StatesInfo"),
)
def _precision_recall(ctx, ins, attrs):
    """Multi-class precision/recall (precision_recall_op.cc): per-class
    TP/FP/TN/FN accumulation + macro/micro averaged metrics."""
    indices = ins["Indices"][0].reshape(-1).astype(jnp.int32)  # predicted class
    labels = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    cls = attrs["class_number"]
    states = ins["StatesInfo"][0] if ins.get("StatesInfo") else jnp.zeros((cls, 4))
    correct = indices == labels
    tp = jnp.zeros((cls,), jnp.float32).at[labels].add(correct.astype(jnp.float32))
    fp = jnp.zeros((cls,), jnp.float32).at[indices].add((~correct).astype(jnp.float32))
    fn = jnp.zeros((cls,), jnp.float32).at[labels].add((~correct).astype(jnp.float32))
    n = indices.shape[0]
    tn = jnp.full((cls,), float(n)) - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)
    acc_states = states + batch_states

    def metrics(s):
        tp_, fp_, tn_, fn_ = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1.0), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1.0), 0.0)
        f1 = jnp.where(
            prec + rec > 0, 2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0
        )
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        tps, fps, fns = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mprec = jnp.where(tps + fps > 0, tps / jnp.maximum(tps + fps, 1.0), 0.0)
        mrec = jnp.where(tps + fns > 0, tps / jnp.maximum(tps + fns, 1.0), 0.0)
        mf1 = jnp.where(
            mprec + mrec > 0, 2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-12), 0.0
        )
        return jnp.concatenate([macro, jnp.stack([mprec, mrec, mf1])])

    return {
        "BatchMetrics": [metrics(batch_states)],
        "AccumMetrics": [metrics(acc_states)],
        "AccumStatesInfo": [acc_states],
    }


@register("average_accumulates", no_grad_inputs=None)
def _average_accumulates(ctx, ins, attrs):
    """Parameter-averaging accumulator step (average_accumulates_op.cc),
    the engine under ModelAverage (optimizer.py:1365): maintains
    sum_1/sum_2/sum_3 windows of parameter values and step counters."""
    param = ins["param"][0]
    sum1 = ins["in_sum_1"][0]
    sum2 = ins["in_sum_2"][0]
    sum3 = ins["in_sum_3"][0]
    num_updates = ins["in_num_updates"][0].reshape(()).astype(jnp.int32)
    num_accum = ins["in_num_accumulates"][0].reshape(()).astype(jnp.int32)
    old_num_accum = ins["in_old_num_accumulates"][0].reshape(()).astype(jnp.int32)
    avg_window = attrs.get("average_window", 10000.0)
    max_avg_window = attrs.get("max_average_window", 10000)
    min_avg_window = attrs.get("min_average_window", 10000)

    k_max_num_accumulates = 16384  # kMaxNumAccumulates (average_accumulates_op.h)

    num_updates = num_updates + 1
    num_accum = num_accum + 1
    sum1 = sum1 + param
    # overflow guard: periodically shift sum1 into sum2
    shift = (num_updates % k_max_num_accumulates) == 0
    sum2 = jnp.where(shift, sum2 + sum1, sum2)
    sum1 = jnp.where(shift, jnp.zeros_like(sum1), sum1)
    # window roll: sum3 <- sum1 + sum2, counters move to old_num_accumulates
    window = jnp.minimum(
        (num_updates.astype(jnp.float32) * avg_window).astype(jnp.int32),
        max_avg_window,
    )
    roll = (num_accum >= min_avg_window) & (num_accum >= window)
    sum3 = jnp.where(roll, sum1 + sum2, sum3)
    sum1 = jnp.where(roll, jnp.zeros_like(sum1), sum1)
    sum2 = jnp.where(roll, jnp.zeros_like(sum2), sum2)
    old_num_accum = jnp.where(roll, num_accum, old_num_accum)
    num_accum = jnp.where(roll, jnp.int32(0), num_accum)
    return {
        "out_sum_1": [sum1],
        "out_sum_2": [sum2],
        "out_sum_3": [sum3],
        "out_num_accumulates": [num_accum.reshape(1)],
        "out_old_num_accumulates": [old_num_accum.reshape(1)],
        "out_num_updates": [num_updates.reshape(1)],
    }
