"""Pallas TPU kernels: the hand-fused hot-op layer.

Role parity with the reference's specialized kernel libraries — the cuDNN
kernel variants and operators/math/ JIT kernels (SURVEY §2.6 math/,
fused/) — but written for the TPU memory hierarchy: q-blocked
flash attention with online softmax (keeps the [T,T] score matrix out of
HBM) and a row-blocked fused layer_norm.  Backward passes use custom_vjp
with XLA-fused recompute (the standard memory-for-FLOPs trade on TPU).

Kernels run compiled on TPU and in interpreter mode elsewhere, so the same
code path is unit-testable on the CPU mesh.  Dispatch happens inside the
regular op lowerings when FLAGS_use_pallas is on (the analog of the
reference's OpKernelType.library_type kernel override).
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def _cdiv(a, b):
    return (a + b - 1) // b


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale,
                      q_block):
    """One (batch*head, q_block) cell: online softmax over k blocks.
    q_ref: [bq, d]; k_ref/v_ref: [T, d] (whole sequence resident in VMEM)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # block refs: [1, bq, d]
    _, T, d = k_ref.shape
    bq = q.shape[0]
    nk = T // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """q/k/v: [BH, T, d] -> o [BH, T, d]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, d = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (
        "flash attention requires seq len %d divisible by block sizes "
        "(%d, %d) — pad the sequence" % (T, block_q, block_k)
    )
    grid = (BH, T // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_k=block_k,
        causal=causal,
        scale=scale,
        q_block=block_q,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v)


def _dense_attention(q, k, v, causal, scale):
    """XLA reference implementation (used for the backward recompute)."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128):
    """Fused attention over [BH, T, d] (flash-style online softmax)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    o = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return o, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # recompute-based backward: XLA fuses the re-derived softmax with the
    # grad matmuls; trades FLOPs for never materializing fwd residuals
    _, vjp = jax.vjp(lambda q, k, v: _dense_attention(q, k, v, causal, scale),
                     q, k, v)
    return vjp(do)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_fwd(x2d, gamma, beta, eps, block_rows=256):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    block_rows = min(block_rows, R)
    if R % block_rows != 0:
        block_rows = 1 if R % 8 else 8
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, H), x2d.dtype),
        interpret=_interpret(),
    )(x2d, gamma, beta)


def _ln_dense(x2d, gamma, beta, eps):
    x = x2d.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x2d, gamma, beta, eps=1e-5):
    """Row-fused layer norm over [rows, hidden]."""
    return _ln_fwd(x2d, gamma, beta, eps)


def _ln_vjp_fwd(x2d, gamma, beta, eps):
    return _ln_fwd(x2d, gamma, beta, eps), (x2d, gamma, beta)


def _ln_vjp_bwd(eps, res, dy):
    x2d, gamma, beta = res
    _, vjp = jax.vjp(lambda x, g, b: _ln_dense(x, g, b, eps), x2d, gamma, beta)
    return vjp(dy)


fused_layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def use_pallas():
    """Kernel-override dispatch switch (OpKernelType.library analog)."""
    from ..flags import get_flag

    return get_flag("use_pallas")
