"""Pallas TPU kernels: the hand-fused hot-op layer.

Role parity with the reference's specialized kernel libraries — the cuDNN
kernel variants and operators/math/ JIT kernels (SURVEY §2.6 math/,
fused/) — but written for the TPU memory hierarchy: q-blocked
flash attention with online softmax (keeps the [T,T] score matrix out of
HBM) and a row-blocked fused layer_norm.  Backward passes use custom_vjp
with XLA-fused recompute (the standard memory-for-FLOPs trade on TPU).

Kernels run compiled on TPU and in interpreter mode elsewhere, so the same
code path is unit-testable on the CPU mesh.  Dispatch happens inside the
regular op lowerings when FLAGS_use_pallas is on (the analog of the
reference's OpKernelType.library_type kernel override).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, *xs):
    """ShapeDtypeStruct whose vma (varying-mesh-axes) is the union of the
    inputs' — so pallas_call out_shapes type-check inside shard_map on
    jax builds with vma tracking; builds without it (this sandbox's
    0.4.x) take neither the kwarg nor the tracking, so plain structs."""
    from ..parallel.mesh import vma_of

    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma_of(*xs))


def _cdiv(a, b):
    return (a + b - 1) // b


def _row_block(n, default):
    """Shared row/batch tiling heuristic: the default block when it
    divides n, else the largest of (8, 1) that does."""
    blk = min(default, n)
    if n % blk != 0:
        blk = 1 if n % 8 else 8
    return blk


# ---------------------------------------------------------------------------
# flash attention
#
# Blocked over BOTH q and k: grid (BH, nq, nk) with the k index innermost
# (sequential on a TPU core), carrying the online-softmax state (acc, m, l)
# in VMEM scratch across k steps.  Only [block, d] tiles of K/V are ever
# resident, so sequence length is bounded by HBM, not VMEM.  The forward
# saves the per-row logsumexp; the backward is two Pallas kernels (dq and
# dk/dv/dkbias) that rebuild [block_q, block_k] probability tiles from the
# saved lse — the [T, T] score matrix never exists in HBM in either pass.
# Role parity: the cuDNN fused-attention kernels of SURVEY §2.6.
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(*refs, block_q, block_k, nk,
                      causal, scale, window=0, has_qoff=False,
                      has_seg=False):
    from jax.experimental import pallas as pl

    refs = list(refs)
    qo = refs.pop(0)[0] if has_qoff else 0  # global q base (SMEM scalar)
    q_ref, k_ref, v_ref, kb_ref = refs[:4]
    del refs[:4]
    sq_ref, sk_ref = (refs[:2] if has_seg else (None, None))
    if has_seg:
        del refs[:2]
    o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run, keep_fn = _band(qi, ki, qo, block_q, block_k, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        s = s + kb_ref[0].astype(jnp.float32)  # [1, bk] broadcast
        if has_seg:  # packing: keep within-segment scores only
            s = jnp.where(
                sq_ref[0].reshape(-1, 1) == sk_ref[0], s, NEG_INF)
        s = keep_fn(s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _write():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:] + jnp.log(safe_l)).reshape(1, -1)


def _band(qi, ki, qo, block_q, block_k, causal, window):
    """Shared causal/window band logic for the three flash kernels:
    returns (run, keep_fn) — the block-skip predicate and a function
    masking an [bq, bk] score tile in GLOBAL positions (q base = qo)."""
    run = (ki * block_k < (qi + 1) * block_q + qo) if causal else (ki >= 0)
    if window:
        run = run & (ki * block_k + block_k - 1
                     >= qi * block_q + qo - window + 1)

    def keep_fn(s):
        if not causal:
            return s
        q_pos = qo + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = q_pos >= k_pos
        if window:
            keep = keep & (q_pos - k_pos < window)
        return jnp.where(keep, s, NEG_INF)

    return run, keep_fn


def _flash_blocks(Tq, Tk, block_q, block_k, causal):
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    assert Tq % block_q == 0 and Tk % block_k == 0, (
        "flash attention requires seq lens (%d, %d) divisible by block "
        "sizes (%d, %d) — pad the sequence" % (Tq, Tk, block_q, block_k)
    )
    assert not (causal and Tq != Tk), "causal requires Tq == Tk"
    return block_q, block_k


def _flash_fwd(q, k, v, kbias, causal, scale, block_q, block_k, window=0,
               qoff=None, seg=None):
    """q: [BH, Tq, d], k/v: [BH, Tk, d], kbias: [BH, Tk] additive key bias.
    window > 0 (causal only): sliding-window attention — each query sees
    only the last `window` key positions.  qoff: optional [1] int32 GLOBAL
    q-position base relative to k's (traced; SMEM scalar) — the ring
    passes its chunk offset so causal/window masks apply in global
    positions.  seg: optional [BH, T] int32 segment ids (sequence
    packing; requires Tq == Tk) — rides as two more [BH, 1, X] rank-1
    operands, compared per score tile.  Returns (o, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, d = q.shape
    Tk = k.shape[1]
    block_q, block_k = _flash_blocks(T, Tk, block_q, block_k,
                                     causal and qoff is None)
    assert not (window and not causal), "window attention requires causal"
    assert seg is None or T == Tk, "segment ids require Tq == Tk"
    nq, nk = T // block_q, Tk // block_k
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, nk=nk,
        causal=causal, scale=scale, window=int(window),
        has_qoff=qoff is not None, has_seg=seg is not None,
    )
    # 2D [BH, X] operands ride as [BH, 1, X] so every block keeps a
    # Mosaic-legal last-two-dims shape ((1, blk): second-minor equals the
    # array dim, minor is the 128-multiple block)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v, kbias.reshape(BH, 1, Tk)]
    if seg is not None:
        seg3 = seg.astype(jnp.int32).reshape(BH, 1, T)
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ]
        args += [seg3, seg3]
    if qoff is not None:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(0, qoff.astype(jnp.int32).reshape(1))
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q, k, v),
            _sds((BH, 1, T), jnp.float32, q, k, v),
        ],  # lse is over q rows; k-side shapes use Tk
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse.reshape(BH, T)


def _flash_dq_kernel(*refs, block_q, block_k, nk, causal, scale,
                     window=0, has_qoff=False, has_seg=False):
    from jax.experimental import pallas as pl

    refs = list(refs)
    qo = refs.pop(0)[0] if has_qoff else 0
    q_ref, k_ref, v_ref, kb_ref = refs[:4]
    del refs[:4]
    sq_ref, sk_ref = (refs[:2] if has_seg else (None, None))
    if has_seg:
        del refs[:2]
    do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run, keep_fn = _band(qi, ki, qo, block_q, block_k, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].reshape(-1, 1)  # [bq, 1]
        delta = delta_ref[0].reshape(-1, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = s + kb_ref[0].astype(jnp.float32)
        if has_seg:
            s = jnp.where(
                sq_ref[0].reshape(-1, 1) == sk_ref[0], s, NEG_INF)
        s = keep_fn(s)
        # rows with NO visible key (possible under qoff+window) carry the
        # lse sentinel: their forward output is defined-garbage by
        # contract, so their grads are 0 — without this guard
        # exp(s - lse) would be 1 on every masked entry of such rows
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[:] = dq_acc[:] + scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _write():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(*refs, block_q, block_k, nq, causal, scale,
                      window=0, has_qoff=False, has_seg=False):
    from jax.experimental import pallas as pl

    refs = list(refs)
    qo = refs.pop(0)[0] if has_qoff else 0
    q_ref, k_ref, v_ref, kb_ref = refs[:4]
    del refs[:4]
    sq_ref, sk_ref = (refs[:2] if has_seg else (None, None))
    if has_seg:
        del refs[:2]
    (do_ref, lse_ref, delta_ref,
     dk_ref, dv_ref, dkb_ref, dk_acc, dv_acc, dkb_acc) = refs
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)
        dkb_acc[:] = jnp.zeros_like(dkb_acc)

    run, keep_fn = _band(qi, ki, qo, block_q, block_k, causal, window)
    if not causal:
        run = qi >= 0  # this grid iterates q innermost

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].reshape(-1, 1)
        delta = delta_ref[0].reshape(-1, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = s + kb_ref[0].astype(jnp.float32)
        if has_seg:
            s = jnp.where(
                sq_ref[0].reshape(-1, 1) == sk_ref[0], s, NEG_INF)
        s = keep_fn(s)
        # undefined-row grad guard (see _flash_dq_kernel)
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc[:] = dv_acc[:] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + scale * jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)
        dkb_acc[:] = dkb_acc[:] + jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(qi == nq - 1)
    def _write():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)
        dkb_ref[0] = dkb_acc[:]  # [1, block_k] both sides


def _flash_bwd(q, k, v, kbias, o, lse, do, causal, scale, block_q, block_k,
               dlse=None, window=0, qoff=None, seg=None):
    """Blocked backward: returns (dq, dk, dv, dkbias[BH,Tk] f32).

    dlse: optional cotangent of the lse output (the chunk-merge path of
    ring attention differentiates through lse).  d lse / d s_ij = p_ij, so
    it folds into the delta term: ds = p * (dp - (delta - dlse))."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, d = q.shape
    Tk = k.shape[1]
    block_q, block_k = _flash_blocks(T, Tk, block_q, block_k,
                                     causal and qoff is None)
    nq, nk = T // block_q, Tk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    qoff_arg = (
        [qoff.astype(jnp.int32).reshape(1)] if qoff is not None else [])
    # 2D [BH, X] operands ride as [BH, 1, X] (Mosaic-legal blocks; see
    # _flash_fwd)
    kb3 = kbias.reshape(BH, 1, Tk)
    lse3 = lse.reshape(BH, 1, T)
    delta3 = delta.reshape(BH, 1, T)
    seg3 = (seg.astype(jnp.int32).reshape(BH, 1, T)
            if seg is not None else None)

    q_spec_q = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    k_spec_q = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    kb_spec_q = pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j),
                             memory_space=pltpu.VMEM)
    row_spec_q = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                              memory_space=pltpu.VMEM)
    smem = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
            if qoff is not None else [])
    seg_specs_q = ([row_spec_q, kb_spec_q] if seg is not None else [])
    seg_args = ([seg3, seg3] if seg is not None else [])
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_q=block_q, block_k=block_k,
                          nk=nk, causal=causal, scale=scale,
                          window=int(window), has_qoff=qoff is not None,
                          has_seg=seg is not None),
        grid=(BH, nq, nk),
        in_specs=smem + [q_spec_q, k_spec_q, k_spec_q, kb_spec_q]
        + seg_specs_q + [q_spec_q, row_spec_q, row_spec_q],
        out_specs=q_spec_q,
        out_shape=_sds((BH, T, d), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*(qoff_arg + [q, k, v, kb3] + seg_args + [do, lse3, delta3]))

    # dk/dv pass: grid iterates q blocks innermost for each k block
    q_spec_k = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    k_spec_k = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    kb_spec_k = pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, i),
                             memory_space=pltpu.VMEM)
    row_spec_k = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, j),
                              memory_space=pltpu.VMEM)
    seg_specs_k = ([row_spec_k, kb_spec_k] if seg is not None else [])
    dk, dv, dkb = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, block_k=block_k,
                          nq=nq, causal=causal, scale=scale,
                          window=int(window), has_qoff=qoff is not None,
                          has_seg=seg is not None),
        grid=(BH, nk, nq),
        in_specs=smem + [q_spec_k, k_spec_k, k_spec_k, kb_spec_k]
        + seg_specs_k + [q_spec_k, row_spec_k, row_spec_k],
        out_specs=[k_spec_k, k_spec_k, kb_spec_k],
        out_shape=[
            _sds((BH, Tk, d), k.dtype, q, k, v, do),
            _sds((BH, Tk, d), v.dtype, q, k, v, do),
            _sds((BH, 1, Tk), jnp.float32, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((1, block_k), jnp.float32),
        ],
        interpret=_interpret(),
    )(*(qoff_arg + [q, k, v, kb3] + seg_args + [do, lse3, delta3]))
    return dq, dk, dv, dkb.reshape(BH, Tk)


def _dense_attention(q, k, v, causal, scale, kbias=None, window=0,
                     seg=None, qoff=None):
    """XLA reference implementation (used as the non-pallas fallback).
    seg: optional [BH, T] int segment ids (sequence packing) — query i
    may attend key j only when seg[i] == seg[j]; the compare fuses into
    the softmax, no mask tensor lives in HBM.  qoff: optional traced
    GLOBAL q-position base (chunked decode): query i sits at global
    position qoff + i, keys at their indices — Tq may differ from Tk."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if kbias is not None:
        s = s + kbias[:, None, :].astype(jnp.float32)
    if seg is not None:
        s = jnp.where(seg[:, :, None] == seg[:, None, :], s, NEG_INF)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        if qoff is not None:
            q_pos = (jnp.asarray(qoff).reshape(()).astype(jnp.int32)
                     + jnp.arange(Tq, dtype=jnp.int32))
            k_pos = jnp.arange(Tk, dtype=jnp.int32)
            keep = q_pos[:, None] >= k_pos[None, :]
            if window:
                keep = keep & (q_pos[:, None] - k_pos[None, :] < int(window))
            s = jnp.where(keep[None], s, NEG_INF)
        else:
            mask = jnp.tril(jnp.ones((Tq, Tq), bool))
            if window:
                mask = mask & ~jnp.tril(jnp.ones((Tq, Tq), bool),
                                        -int(window))
            s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, kbias=None, causal=False, scale=None,
                    block_q=128, block_k=128, window=0, seg=None):
    """Fused attention, q: [BH, Tq, d], k/v: [BH, Tk, d] (flash-style
    online softmax).  kbias: optional [BH, Tk] additive key bias (the
    padding-mask row, indexed by key position).  window > 0 (causal):
    sliding-window local attention over the last `window` positions —
    fully-out-of-window blocks are skipped in all three kernels, so
    compute scales with window, not T.  seg: optional [BH, T] int
    segment ids (sequence packing, Tq == Tk): scores cross segment
    boundaries are masked inside every kernel — rank-1 operands only,
    no [T, T] mask."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(k.shape[:2], jnp.float32)
    o, _ = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                      seg=seg)
    return o


def _flash_vjp_fwd(q, k, v, kbias, causal, scale, block_q, block_k,
                   window=0, seg=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(k.shape[:2], jnp.float32)
    o, lse = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                        seg=seg)
    return o, (q, k, v, kbias, seg, o, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, window, res, do):
    q, k, v, kbias, seg, o, lse = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(k.shape[:2], jnp.float32)
    dq, dk, dv, dkb = _flash_bwd(
        q, k, v, kb, o, lse, do, causal, scale, block_q, block_k,
        window=window, seg=seg)
    # integer segment ids get the mandatory float0 cotangent
    dseg = (None if seg is None
            else np.zeros(seg.shape, dtype=jax.dtypes.float0))
    dkb_out = None if kbias is None else dkb.astype(kbias.dtype)
    return dq, dk, dv, dkb_out, dseg


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_piece(q, k, v, causal=False, scale=None,
                          block_q=128, block_k=128, window=0, qoff=None):
    """Unmerged attention piece for ring/Ulysses sequence parallelism:
    returns (o, lse) where o is softmax-normalized within this K/V chunk
    and lse is the per-row logsumexp.  Two pieces merge exactly via
    lse = logaddexp(lse1, lse2); o = o1*exp(lse1-lse) + o2*exp(lse2-lse)
    (see parallel/ring.py).  Differentiable in q/k/v including through the
    lse output (its cotangent folds into the backward's delta term).
    window/qoff: sliding-window masking and a traced GLOBAL q-position
    offset (SMEM scalar), so ring callers mask diagonal AND off-diagonal
    chunks exactly in global positions."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    return _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                      qoff)


def _piece_vjp_fwd(q, k, v, causal, scale, block_q, block_k, window=0,
                   qoff=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    o, lse = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                        qoff)
    return (o, lse), (q, k, v, o, lse, qoff)


def _piece_vjp_bwd(causal, scale, block_q, block_k, window, res, cts):
    q, k, v, o, lse, qoff = res
    do, dlse = cts
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    dq, dk, dv, _ = _flash_bwd(
        q, k, v, kb, o, lse, do, causal, scale, block_q, block_k, dlse=dlse,
        window=window, qoff=qoff)
    return dq, dk, dv, None


flash_attention_piece.defvjp(_piece_vjp_fwd, _piece_vjp_bwd)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_fwd(x2d, gamma, beta, eps, block_rows=256):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    block_rows = _row_block(R, block_rows)
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, H), x2d.dtype),
        interpret=_interpret(),
    )(x2d, gamma, beta)


def _ln_dense(x2d, gamma, beta, eps):
    x = x2d.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x2d, gamma, beta, eps=1e-5):
    """Row-fused layer norm over [rows, hidden]."""
    return _ln_fwd(x2d, gamma, beta, eps)


def _ln_vjp_fwd(x2d, gamma, beta, eps):
    return _ln_fwd(x2d, gamma, beta, eps), (x2d, gamma, beta)


def _ln_vjp_bwd(eps, res, dy):
    x2d, gamma, beta = res
    _, vjp = jax.vjp(lambda x, g, b: _ln_dense(x, g, b, eps), x2d, gamma, beta)
    return vjp(dy)


fused_layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def use_pallas():
    """Kernel-override dispatch switch (OpKernelType.library analog)."""
    from ..flags import get_flag

    return get_flag("use_pallas")


# ---------------------------------------------------------------------------
# fused GRU sequence kernel (math/jit_kernel.h gru kernels + fused/fusion_gru
# analog): the hidden state lives in VMEM across ALL timesteps, so the
# recurrence reads/writes HBM once per sequence instead of once per step
# ---------------------------------------------------------------------------
def _gru_seq_kernel(x_ref, w_ref, h0_ref, len_ref, o_ref, *, hid, seq_len):
    w = w_ref[:].astype(jnp.float32)  # [H, 3H]
    w_uz = w[:, : 2 * hid]
    w_c = w[:, 2 * hid:]
    lens = len_ref[:].astype(jnp.int32).reshape(-1)  # [Bblk, 1] -> [Bblk]

    def step(t, h):
        xt = x_ref[:, t, :].astype(jnp.float32)  # [Bblk, 3H]
        gates = xt[:, : 2 * hid] + jax.lax.dot(
            h, w_uz, preferred_element_type=jnp.float32
        )
        u = jax.nn.sigmoid(gates[:, :hid])
        r = jax.nn.sigmoid(gates[:, hid:])
        c = jnp.tanh(
            xt[:, 2 * hid:]
            + jax.lax.dot(r * h, w_c, preferred_element_type=jnp.float32)
        )
        h_new = u * c + (1.0 - u) * h
        active = (t < lens)[:, None].astype(jnp.float32)
        h_new = active * h_new + (1.0 - active) * h
        o_ref[:, t, :] = h_new.astype(o_ref.dtype)
        return h_new

    jax.lax.fori_loop(0, seq_len, step, h0_ref[:].astype(jnp.float32))


def _gru_seq_fwd(xproj, w, h0, lens, block_b=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H3 = xproj.shape
    hid = H3 // 3
    block_b = _row_block(B, block_b)
    grid = (_cdiv(B, block_b),)
    return pl.pallas_call(
        functools.partial(_gru_seq_kernel, hid=hid, seq_len=T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, T, H3), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, H3), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, hid), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            # lens rides as [B, 1]: a 1D (block_b,) block is Mosaic-illegal
            # for block_b < 128; (block_b, 1) matches the array's last dim
            pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_b, T, hid), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
        interpret=_interpret(),
    )(xproj, w, h0, lens.reshape(B, 1))


def _gru_seq_dense(xproj, w, h0, lens):
    """Reference scan (also the recompute path for the backward pass)."""
    hid = xproj.shape[-1] // 3
    w_uz, w_c = w[:, : 2 * hid], w[:, 2 * hid:]

    def step(h, inp):
        xt, t = inp
        gates = xt[:, : 2 * hid] + h @ w_uz
        u = jax.nn.sigmoid(gates[:, :hid])
        r = jax.nn.sigmoid(gates[:, hid:])
        c = jnp.tanh(xt[:, 2 * hid:] + (r * h) @ w_c)
        h_new = u * c + (1.0 - u) * h
        act = (t < lens)[:, None].astype(h.dtype)
        h_new = act * h_new + (1 - act) * h
        return h_new, h_new

    xs = jnp.swapaxes(xproj, 0, 1)
    ts = jnp.arange(xproj.shape[1])
    _, hs = jax.lax.scan(step, h0, (xs, ts))
    return jnp.swapaxes(hs, 0, 1)


@jax.custom_vjp
def fused_gru(xproj, w, h0, lens):
    """VMEM-resident GRU over padded [B, T, 3H] projected inputs."""
    return _gru_seq_fwd(xproj, w, h0, lens)


def _gru_vjp_fwd(xproj, w, h0, lens):
    return _gru_seq_fwd(xproj, w, h0, lens), (xproj, w, h0, lens)


def _gru_vjp_bwd(res, dy):
    xproj, w, h0, lens = res
    _, vjp = jax.vjp(lambda x, w_, h_: _gru_seq_dense(x, w_, h_, lens),
                     xproj, w, h0)
    dx, dw, dh0 = vjp(dy)
    return dx, dw, dh0, None


fused_gru.defvjp(_gru_vjp_fwd, _gru_vjp_bwd)


# ---------------------------------------------------------------------------
# fused LSTM sequence kernel (math/jit_kernel.h lstm kernels +
# fused/fusion_lstm analog): hidden AND cell state live in VMEM across all
# timesteps — one HBM read of the projected gates and one write of each
# output sequence per batch block, instead of per-step round trips
# ---------------------------------------------------------------------------
def _lstm_seq_kernel(x_ref, w_ref, h0_ref, c0_ref, len_ref, o_ref, cell_ref,
                     *, hid, seq_len):
    w = w_ref[:].astype(jnp.float32)  # [H, 4H]
    lens = len_ref[:].astype(jnp.int32).reshape(-1)  # [Bblk, 1] -> [Bblk]

    def step(t, hc):
        h, c = hc
        xt = x_ref[:, t, :].astype(jnp.float32)  # [Bblk, 4H]
        gates = xt + jax.lax.dot(h, w, preferred_element_type=jnp.float32)
        # gate order i|f|c_hat|o (lstm_op.cc / _lstm_cell layout)
        i = jax.nn.sigmoid(gates[:, :hid])
        f = jax.nn.sigmoid(gates[:, hid: 2 * hid])
        c_hat = jnp.tanh(gates[:, 2 * hid: 3 * hid])
        o = jax.nn.sigmoid(gates[:, 3 * hid:])
        c_new = f * c + i * c_hat
        h_new = o * jnp.tanh(c_new)
        active = (t < lens)[:, None].astype(jnp.float32)
        c_new = active * c_new + (1.0 - active) * c
        h_new = active * h_new + (1.0 - active) * h
        o_ref[:, t, :] = h_new.astype(o_ref.dtype)
        cell_ref[:, t, :] = c_new.astype(cell_ref.dtype)
        return (h_new, c_new)

    jax.lax.fori_loop(
        0, seq_len, step,
        (h0_ref[:].astype(jnp.float32), c0_ref[:].astype(jnp.float32)),
    )


def _lstm_seq_fwd(xproj, w, h0, c0, lens, block_b=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H4 = xproj.shape
    hid = H4 // 4
    block_b = _row_block(B, block_b)
    grid = (_cdiv(B, block_b),)
    state_spec = pl.BlockSpec((block_b, hid), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    seq_spec = pl.BlockSpec((block_b, T, hid), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_lstm_seq_kernel, hid=hid, seq_len=T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, T, H4), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, H4), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            state_spec,
            state_spec,
            # lens rides as [B, 1] (1D sub-128 blocks are Mosaic-illegal)
            pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[seq_spec, seq_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
            jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
        ],
        interpret=_interpret(),
    )(xproj, w, h0, c0, lens.reshape(B, 1))


def _lstm_seq_dense(xproj, w, h0, c0, lens):
    """Reference scan (also the recompute path for the backward pass).
    Reuses nn_ops._lstm_cell — one copy of the gate math outside the
    hand-tiled kernel (which must slice refs explicitly)."""
    from .nn_ops import _lstm_cell  # lazy: nn_ops imports this module

    def step(carry, inp):
        h, c = carry
        xt, t = inp
        gates = xt + h @ w
        c_new, h_new = _lstm_cell(c, h, gates)
        act = (t < lens)[:, None].astype(h.dtype)
        c_new = act * c_new + (1 - act) * c
        h_new = act * h_new + (1 - act) * h
        return (h_new, c_new), (h_new, c_new)

    xs = jnp.swapaxes(xproj, 0, 1)
    ts = jnp.arange(xproj.shape[1])
    _, (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, ts))
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


@jax.custom_vjp
def fused_lstm(xproj, w, h0, c0, lens):
    """VMEM-resident LSTM over padded [B, T, 4H] projected inputs;
    returns (hidden_seq, cell_seq), each [B, T, H]."""
    return _lstm_seq_fwd(xproj, w, h0, c0, lens)


def _lstm_vjp_fwd(xproj, w, h0, c0, lens):
    return _lstm_seq_fwd(xproj, w, h0, c0, lens), (xproj, w, h0, c0, lens)


def _lstm_vjp_bwd(res, dy):
    xproj, w, h0, c0, lens = res
    _, vjp = jax.vjp(
        lambda x, w_, h_, c_: _lstm_seq_dense(x, w_, h_, c_, lens),
        xproj, w, h0, c0,
    )
    dx, dw, dh0, dc0 = vjp(dy)
    return dx, dw, dh0, dc0, None


fused_lstm.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)


# ---------------------------------------------------------------------------
# fused softmax cross entropy (row-blocked logsumexp + label gather; the
# backward is the analytic softmax(x) - onehot, no recompute needed)
# ---------------------------------------------------------------------------
def _sxent_kernel(x_ref, lbl_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)  # [Bblk, C]
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    lbl = lbl_ref[:].astype(jnp.int32).reshape(-1)  # [Bblk, 1] -> [Bblk]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    gold = jnp.sum(jnp.where(cols == lbl[:, None], x, 0.0), axis=-1,
                   keepdims=True)
    o_ref[:] = (lse - gold).astype(o_ref.dtype)


def _sxent_fwd_call(logits, labels, block_rows=512):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = logits.shape
    block_rows = _row_block(R, block_rows)
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        _sxent_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            # labels ride as [R, 1] (1D sub-128 blocks are Mosaic-illegal)
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.float32),
        interpret=_interpret(),
    )(logits, labels.reshape(R, 1))


@jax.custom_vjp
def fused_softmax_xent(logits, labels):
    """Per-row -log softmax[label] over [rows, classes] + int labels [rows]."""
    return _sxent_fwd_call(logits, labels)


def _sxent_vjp_fwd(logits, labels):
    return _sxent_fwd_call(logits, labels), (logits, labels)


def _sxent_vjp_bwd(res, dy):
    logits, labels = res
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return ((p - onehot) * dy.astype(jnp.float32)).astype(logits.dtype), None


fused_softmax_xent.defvjp(_sxent_vjp_fwd, _sxent_vjp_bwd)
