"""Pallas TPU kernels: the hand-fused hot-op layer.

Role parity with the reference's specialized kernel libraries — the cuDNN
kernel variants and operators/math/ JIT kernels (SURVEY §2.6 math/,
fused/) — but written for the TPU memory hierarchy: q-blocked
flash attention with online softmax (keeps the [T,T] score matrix out of
HBM) and a row-blocked fused layer_norm.  Backward passes use custom_vjp
with XLA-fused recompute (the standard memory-for-FLOPs trade on TPU).

Kernels run compiled on TPU and in interpreter mode elsewhere, so the same
code path is unit-testable on the CPU mesh.  Dispatch happens inside the
regular op lowerings when FLAGS_use_pallas is on (the analog of the
reference's OpKernelType.library_type kernel override).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

# the public primitive-kernel surface (tools/print_signatures tracks it
# in API.spec): the closed composable set the hot paths dispatch to
__all__ = [
    "flash_attention",
    "flash_attention_piece",
    "flash_attention_qvec",
    "fused_layer_norm",
    "fused_add_layer_norm",
    "fused_gru",
    "fused_lstm",
    "fused_softmax_xent",
    "fused_linear_xent",
    "matmul_bias_act",
    "matmul_swiglu",
    "use_pallas",
]

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, *xs):
    """ShapeDtypeStruct whose vma (varying-mesh-axes) is the union of the
    inputs' — so pallas_call out_shapes type-check inside shard_map on
    jax builds with vma tracking; builds without it (this sandbox's
    0.4.x) take neither the kwarg nor the tracking, so plain structs."""
    from ..parallel.mesh import vma_of

    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma_of(*xs))


def _cdiv(a, b):
    return (a + b - 1) // b


def _row_block(n, default):
    """Shared row/batch tiling heuristic: the default block when it
    divides n, else the largest of (8, 1) that does."""
    blk = min(default, n)
    if n % blk != 0:
        blk = 1 if n % 8 else 8
    return blk


def _note(family, n=1):
    """Trace-time pallas dispatch counter (bench attribution)."""
    from .kernel_tuning import note_kernel

    note_kernel(family, n)


def _tuned(kernel, shapes, dtype, candidates, default, build=None,
           arg_specs=None):
    """Consult the persisted tuning cache for this call site's block
    sizes; on a real-device miss with FLAGS_kernel_autotune, time the
    candidates on synthetic operands via `build(params) -> callable over
    arg_specs arrays`.  Interpret-mode misses seed `default`."""
    from . import kernel_tuning as kt

    measure = None
    if build is not None and arg_specs and not _interpret():
        measure = kt.measure_candidate(build, arg_specs)
    return kt.tuned_params(kernel, shapes, str(dtype), candidates, default,
                           measure)


def _row_block_candidates(n, sizes=(128, 256, 512, 1024)):
    """Row-block search space: the legal (dividing) members of `sizes`."""
    return [{"block_rows": s} for s in sizes if s <= n and n % s == 0]


# ---------------------------------------------------------------------------
# flash attention
#
# Blocked over BOTH q and k: grid (BH, nq, nk) with the k index innermost
# (sequential on a TPU core), carrying the online-softmax state (acc, m, l)
# in VMEM scratch across k steps.  Only [block, d] tiles of K/V are ever
# resident, so sequence length is bounded by HBM, not VMEM.  The forward
# saves the per-row logsumexp; the backward is two Pallas kernels (dq and
# dk/dv/dkbias) that rebuild [block_q, block_k] probability tiles from the
# saved lse — the [T, T] score matrix never exists in HBM in either pass.
# Role parity: the cuDNN fused-attention kernels of SURVEY §2.6.
# ---------------------------------------------------------------------------
def _unpack_flash_refs(refs, has_qoff, has_seg):
    """Shared operand unpack for the three flash kernels (fwd/dq/dkv):
    the optional leading q base — SMEM scalar ([1] whole-array), or
    per-row [BH, 1] blocked (1, 1) when has_qoff == "vec" (each grid-b
    cell reads ITS row's base — the vector-qstart ragged serving
    step) — then q/k/v/kbias and the optional segment-id pair.
    Returns (qo, q, k, v, kbias, seg_q, seg_k, remaining_refs); ONE
    copy so a new qstart encoding cannot silently miss a backward
    kernel's causal base."""
    refs = list(refs)
    if has_qoff == "vec":
        qo = refs.pop(0)[0, 0]
    elif has_qoff:
        qo = refs.pop(0)[0]
    else:
        qo = 0
    q_ref, k_ref, v_ref, kb_ref = refs[:4]
    del refs[:4]
    sq_ref, sk_ref = (refs[:2] if has_seg else (None, None))
    if has_seg:
        del refs[:2]
    return qo, q_ref, k_ref, v_ref, kb_ref, sq_ref, sk_ref, refs


def _flash_fwd_kernel(*refs, block_q, block_k, nk,
                      causal, scale, window=0, has_qoff=False,
                      has_seg=False):
    from jax.experimental import pallas as pl

    qo, q_ref, k_ref, v_ref, kb_ref, sq_ref, sk_ref, refs = \
        _unpack_flash_refs(refs, has_qoff, has_seg)
    o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run, keep_fn = _band(qi, ki, qo, block_q, block_k, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        s = s + kb_ref[0].astype(jnp.float32)  # [1, bk] broadcast
        if has_seg:  # packing: keep within-segment scores only
            s = jnp.where(
                sq_ref[0].reshape(-1, 1) == sk_ref[0], s, NEG_INF)
        s = keep_fn(s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _write():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:] + jnp.log(safe_l)).reshape(1, -1)


def _band(qi, ki, qo, block_q, block_k, causal, window):
    """Shared causal/window band logic for the three flash kernels:
    returns (run, keep_fn) — the block-skip predicate and a function
    masking an [bq, bk] score tile in GLOBAL positions (q base = qo)."""
    run = (ki * block_k < (qi + 1) * block_q + qo) if causal else (ki >= 0)
    if window:
        run = run & (ki * block_k + block_k - 1
                     >= qi * block_q + qo - window + 1)

    def keep_fn(s):
        if not causal:
            return s
        q_pos = qo + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = q_pos >= k_pos
        if window:
            keep = keep & (q_pos - k_pos < window)
        return jnp.where(keep, s, NEG_INF)

    return run, keep_fn


def _flash_blocks(Tq, Tk, block_q, block_k, causal):
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    assert Tq % block_q == 0 and Tk % block_k == 0, (
        "flash attention requires seq lens (%d, %d) divisible by block "
        "sizes (%d, %d) — pad the sequence" % (Tq, Tk, block_q, block_k)
    )
    assert not (causal and Tq != Tk), "causal requires Tq == Tk"
    return block_q, block_k


def _flash_fwd(q, k, v, kbias, causal, scale, block_q, block_k, window=0,
               qoff=None, seg=None, qvec=None):
    """q: [BH, Tq, d], k/v: [BH, Tk, d], kbias: [BH, Tk] additive key bias.
    window > 0 (causal only): sliding-window attention — each query sees
    only the last `window` key positions.  qoff: optional [1] int32 GLOBAL
    q-position base relative to k's (traced; SMEM scalar) — the ring
    passes its chunk offset so causal/window masks apply in global
    positions.  qvec: optional [BH] int32 PER-ROW q-position bases (the
    continuous-batching ragged step: every serving slot carries its own
    causal cutoff) riding as [BH, 1] SMEM blocks — mutually exclusive
    with qoff.  seg: optional [BH, T] int32 segment ids (sequence
    packing; requires Tq == Tk) — rides as two more [BH, 1, X] rank-1
    operands, compared per score tile.  Returns (o, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, d = q.shape
    Tk = k.shape[1]
    assert qoff is None or qvec is None, "qoff and qvec are exclusive"
    block_q, block_k = _flash_blocks(T, Tk, block_q, block_k,
                                     causal and qoff is None
                                     and qvec is None)
    assert not (window and not causal), "window attention requires causal"
    assert seg is None or T == Tk, "segment ids require Tq == Tk"
    _note("attention")
    nq, nk = T // block_q, Tk // block_k
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, nk=nk,
        causal=causal, scale=scale, window=int(window),
        has_qoff=("vec" if qvec is not None else qoff is not None),
        has_seg=seg is not None,
    )
    # 2D [BH, X] operands ride as [BH, 1, X] so every block keeps a
    # Mosaic-legal last-two-dims shape ((1, blk): second-minor equals the
    # array dim, minor is the 128-multiple block)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v, kbias.reshape(BH, 1, Tk)]
    if seg is not None:
        seg3 = seg.astype(jnp.int32).reshape(BH, 1, T)
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ]
        args += [seg3, seg3]
    if qoff is not None:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(0, qoff.astype(jnp.int32).reshape(1))
    elif qvec is not None:
        in_specs.insert(0, pl.BlockSpec((1, 1), lambda b, i, j: (b, 0),
                                        memory_space=pltpu.SMEM))
        args.insert(0, qvec.astype(jnp.int32).reshape(BH, 1))
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q, k, v),
            _sds((BH, 1, T), jnp.float32, q, k, v),
        ],  # lse is over q rows; k-side shapes use Tk
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse.reshape(BH, T)


def _flash_dq_kernel(*refs, block_q, block_k, nk, causal, scale,
                     window=0, has_qoff=False, has_seg=False):
    from jax.experimental import pallas as pl

    qo, q_ref, k_ref, v_ref, kb_ref, sq_ref, sk_ref, refs = \
        _unpack_flash_refs(refs, has_qoff, has_seg)
    do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run, keep_fn = _band(qi, ki, qo, block_q, block_k, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].reshape(-1, 1)  # [bq, 1]
        delta = delta_ref[0].reshape(-1, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = s + kb_ref[0].astype(jnp.float32)
        if has_seg:
            s = jnp.where(
                sq_ref[0].reshape(-1, 1) == sk_ref[0], s, NEG_INF)
        s = keep_fn(s)
        # rows with NO visible key (possible under qoff+window) carry the
        # lse sentinel: their forward output is defined-garbage by
        # contract, so their grads are 0 — without this guard
        # exp(s - lse) would be 1 on every masked entry of such rows
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[:] = dq_acc[:] + scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _write():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(*refs, block_q, block_k, nq, causal, scale,
                      window=0, has_qoff=False, has_seg=False):
    from jax.experimental import pallas as pl

    qo, q_ref, k_ref, v_ref, kb_ref, sq_ref, sk_ref, refs = \
        _unpack_flash_refs(refs, has_qoff, has_seg)
    (do_ref, lse_ref, delta_ref,
     dk_ref, dv_ref, dkb_ref, dk_acc, dv_acc, dkb_acc) = refs
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)
        dkb_acc[:] = jnp.zeros_like(dkb_acc)

    run, keep_fn = _band(qi, ki, qo, block_q, block_k, causal, window)
    if not causal:
        run = qi >= 0  # this grid iterates q innermost

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].reshape(-1, 1)
        delta = delta_ref[0].reshape(-1, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = s + kb_ref[0].astype(jnp.float32)
        if has_seg:
            s = jnp.where(
                sq_ref[0].reshape(-1, 1) == sk_ref[0], s, NEG_INF)
        s = keep_fn(s)
        # undefined-row grad guard (see _flash_dq_kernel)
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc[:] = dv_acc[:] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + scale * jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)
        dkb_acc[:] = dkb_acc[:] + jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(qi == nq - 1)
    def _write():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)
        dkb_ref[0] = dkb_acc[:]  # [1, block_k] both sides


def _flash_bwd(q, k, v, kbias, o, lse, do, causal, scale, block_q, block_k,
               dlse=None, window=0, qoff=None, seg=None, qvec=None):
    """Blocked backward: returns (dq, dk, dv, dkbias[BH,Tk] f32).

    dlse: optional cotangent of the lse output (the chunk-merge path of
    ring attention differentiates through lse).  d lse / d s_ij = p_ij, so
    it folds into the delta term: ds = p * (dp - (delta - dlse))."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, d = q.shape
    Tk = k.shape[1]
    assert qoff is None or qvec is None, "qoff and qvec are exclusive"
    block_q, block_k = _flash_blocks(T, Tk, block_q, block_k,
                                     causal and qoff is None
                                     and qvec is None)
    nq, nk = T // block_q, Tk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    qoff_arg = (
        [qoff.astype(jnp.int32).reshape(1)] if qoff is not None
        else [qvec.astype(jnp.int32).reshape(BH, 1)]
        if qvec is not None else [])
    has_qoff = "vec" if qvec is not None else qoff is not None
    # 2D [BH, X] operands ride as [BH, 1, X] (Mosaic-legal blocks; see
    # _flash_fwd)
    kb3 = kbias.reshape(BH, 1, Tk)
    lse3 = lse.reshape(BH, 1, T)
    delta3 = delta.reshape(BH, 1, T)
    seg3 = (seg.astype(jnp.int32).reshape(BH, 1, T)
            if seg is not None else None)

    q_spec_q = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    k_spec_q = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    kb_spec_q = pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j),
                             memory_space=pltpu.VMEM)
    row_spec_q = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                              memory_space=pltpu.VMEM)
    smem = ([pl.BlockSpec(memory_space=pltpu.SMEM)] if qoff is not None
            else [pl.BlockSpec((1, 1), lambda b, i, j: (b, 0),
                               memory_space=pltpu.SMEM)]
            if qvec is not None else [])
    seg_specs_q = ([row_spec_q, kb_spec_q] if seg is not None else [])
    seg_args = ([seg3, seg3] if seg is not None else [])
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_q=block_q, block_k=block_k,
                          nk=nk, causal=causal, scale=scale,
                          window=int(window), has_qoff=has_qoff,
                          has_seg=seg is not None),
        grid=(BH, nq, nk),
        in_specs=smem + [q_spec_q, k_spec_q, k_spec_q, kb_spec_q]
        + seg_specs_q + [q_spec_q, row_spec_q, row_spec_q],
        out_specs=q_spec_q,
        out_shape=_sds((BH, T, d), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*(qoff_arg + [q, k, v, kb3] + seg_args + [do, lse3, delta3]))

    # dk/dv pass: grid iterates q blocks innermost for each k block
    q_spec_k = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    k_spec_k = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    kb_spec_k = pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, i),
                             memory_space=pltpu.VMEM)
    row_spec_k = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, j),
                              memory_space=pltpu.VMEM)
    seg_specs_k = ([row_spec_k, kb_spec_k] if seg is not None else [])
    dk, dv, dkb = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, block_k=block_k,
                          nq=nq, causal=causal, scale=scale,
                          window=int(window), has_qoff=has_qoff,
                          has_seg=seg is not None),
        grid=(BH, nk, nq),
        in_specs=smem + [q_spec_k, k_spec_k, k_spec_k, kb_spec_k]
        + seg_specs_k + [q_spec_k, row_spec_k, row_spec_k],
        out_specs=[k_spec_k, k_spec_k, kb_spec_k],
        out_shape=[
            _sds((BH, Tk, d), k.dtype, q, k, v, do),
            _sds((BH, Tk, d), v.dtype, q, k, v, do),
            _sds((BH, 1, Tk), jnp.float32, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((1, block_k), jnp.float32),
        ],
        interpret=_interpret(),
    )(*(qoff_arg + [q, k, v, kb3] + seg_args + [do, lse3, delta3]))
    return dq, dk, dv, dkb.reshape(BH, Tk)


def _dense_attention(q, k, v, causal, scale, kbias=None, window=0,
                     seg=None, qoff=None):
    """XLA reference implementation (used as the non-pallas fallback).
    seg: optional [BH, T] int segment ids (sequence packing) — query i
    may attend key j only when seg[i] == seg[j]; the compare fuses into
    the softmax, no mask tensor lives in HBM.  qoff: optional traced
    GLOBAL q-position base (chunked decode): query i sits at global
    position qoff + i, keys at their indices — Tq may differ from Tk."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if kbias is not None:
        s = s + kbias[:, None, :].astype(jnp.float32)
    if seg is not None:
        s = jnp.where(seg[:, :, None] == seg[:, None, :], s, NEG_INF)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        if qoff is not None:
            q_pos = (jnp.asarray(qoff).reshape(()).astype(jnp.int32)
                     + jnp.arange(Tq, dtype=jnp.int32))
            k_pos = jnp.arange(Tk, dtype=jnp.int32)
            keep = q_pos[:, None] >= k_pos[None, :]
            if window:
                keep = keep & (q_pos[:, None] - k_pos[None, :] < int(window))
            s = jnp.where(keep[None], s, NEG_INF)
        else:
            mask = jnp.tril(jnp.ones((Tq, Tq), bool))
            if window:
                mask = mask & ~jnp.tril(jnp.ones((Tq, Tq), bool),
                                        -int(window))
            s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, kbias=None, causal=False, scale=None,
                    block_q=128, block_k=128, window=0, seg=None):
    """Fused attention, q: [BH, Tq, d], k/v: [BH, Tk, d] (flash-style
    online softmax).  kbias: optional [BH, Tk] additive key bias (the
    padding-mask row, indexed by key position).  window > 0 (causal):
    sliding-window local attention over the last `window` positions —
    fully-out-of-window blocks are skipped in all three kernels, so
    compute scales with window, not T.  seg: optional [BH, T] int
    segment ids (sequence packing, Tq == Tk): scores cross segment
    boundaries are masked inside every kernel — rank-1 operands only,
    no [T, T] mask."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(k.shape[:2], jnp.float32)
    o, _ = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                      seg=seg)
    return o


def _flash_vjp_fwd(q, k, v, kbias, causal, scale, block_q, block_k,
                   window=0, seg=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(k.shape[:2], jnp.float32)
    o, lse = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                        seg=seg)
    return o, (q, k, v, kbias, seg, o, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, window, res, do):
    q, k, v, kbias, seg, o, lse = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(k.shape[:2], jnp.float32)
    dq, dk, dv, dkb = _flash_bwd(
        q, k, v, kb, o, lse, do, causal, scale, block_q, block_k,
        window=window, seg=seg)
    # integer segment ids get the mandatory float0 cotangent
    dseg = (None if seg is None
            else np.zeros(seg.shape, dtype=jax.dtypes.float0))
    dkb_out = None if kbias is None else dkb.astype(kbias.dtype)
    return dq, dk, dv, dkb_out, dseg


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_piece(q, k, v, causal=False, scale=None,
                          block_q=128, block_k=128, window=0, qoff=None):
    """Unmerged attention piece for ring/Ulysses sequence parallelism:
    returns (o, lse) where o is softmax-normalized within this K/V chunk
    and lse is the per-row logsumexp.  Two pieces merge exactly via
    lse = logaddexp(lse1, lse2); o = o1*exp(lse1-lse) + o2*exp(lse2-lse)
    (see parallel/ring.py).  Differentiable in q/k/v including through the
    lse output (its cotangent folds into the backward's delta term).
    window/qoff: sliding-window masking and a traced GLOBAL q-position
    offset (SMEM scalar), so ring callers mask diagonal AND off-diagonal
    chunks exactly in global positions."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    return _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                      qoff)


def _piece_vjp_fwd(q, k, v, causal, scale, block_q, block_k, window=0,
                   qoff=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    o, lse = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k, window,
                        qoff)
    return (o, lse), (q, k, v, o, lse, qoff)


def _piece_vjp_bwd(causal, scale, block_q, block_k, window, res, cts):
    q, k, v, o, lse, qoff = res
    do, dlse = cts
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    dq, dk, dv, _ = _flash_bwd(
        q, k, v, kb, o, lse, do, causal, scale, block_q, block_k, dlse=dlse,
        window=window, qoff=qoff)
    return dq, dk, dv, None


flash_attention_piece.defvjp(_piece_vjp_fwd, _piece_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention_qvec(q, k, v, qstart, scale=None, block_q=128,
                         block_k=128):
    """PER-ROW-qstart causal flash attention: q [BH, Tq, d] against
    k/v [BH, Tk, d] where row b's query i sits at global position
    qstart[b] + i and keys at their cache indices (Tq may differ from
    Tk).  qstart: [BH] int — rides as [BH, 1] SMEM blocks, so each grid
    cell reads ITS row's causal cutoff; out-of-band K blocks are still
    skipped per row.  This is the ragged continuous-batching serving
    step's attention (PR 9's documented single biggest serving-perf
    lever): one dispatch serves a pool of requests at heterogeneous
    positions without the [B, Tq, Tk] mask or score matrix ever
    existing in HBM.  Row math is row-independent (the serving
    exactness contract: a slot's output is bit-identical to the same
    row running solo).  Shares the band machinery (_band) with the
    training kernels; differentiable in q/k/v for draft-training and
    prefix-tuning setups that backprop through ragged steps."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    o, _ = _flash_fwd(q, k, v, kb, True, scale, block_q, block_k,
                      qvec=qstart)
    return o


def _qvec_vjp_fwd(q, k, v, qstart, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    o, lse = _flash_fwd(q, k, v, kb, True, scale, block_q, block_k,
                        qvec=qstart)
    return o, (q, k, v, qstart, o, lse)


def _qvec_vjp_bwd(scale, block_q, block_k, res, do):
    q, k, v, qstart, o, lse = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = jnp.zeros(k.shape[:2], jnp.float32)
    dq, dk, dv, _ = _flash_bwd(q, k, v, kb, o, lse, do, True, scale,
                               block_q, block_k, qvec=qstart)
    # integer positions get the mandatory float0 cotangent
    dqs = np.zeros(qstart.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dqs


flash_attention_qvec.defvjp(_qvec_vjp_fwd, _qvec_vjp_bwd)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_fwd(x2d, gamma, beta, eps, block_rows=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    if block_rows is None:
        block_rows = _tuned(
            "layer_norm", [x2d.shape], x2d.dtype,
            _row_block_candidates(R),
            {"block_rows": _row_block(R, 256)},
            build=lambda p: (lambda x, g, b: _ln_fwd(
                x, g, b, eps, p["block_rows"])),
            arg_specs=[(x2d.shape, x2d.dtype), (gamma.shape, gamma.dtype),
                       (beta.shape, beta.dtype)],
        )["block_rows"]
    _note("layernorm")
    block_rows = _row_block(R, block_rows)
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, H), x2d.dtype),
        interpret=_interpret(),
    )(x2d, gamma, beta)


def _ln_dense(x2d, gamma, beta, eps):
    x = x2d.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x2d, gamma, beta, eps=1e-5):
    """Row-fused layer norm over [rows, hidden]."""
    return _ln_fwd(x2d, gamma, beta, eps)


def _ln_vjp_fwd(x2d, gamma, beta, eps):
    return _ln_fwd(x2d, gamma, beta, eps), (x2d, gamma, beta)


def _ln_vjp_bwd(eps, res, dy):
    x2d, gamma, beta = res
    _, vjp = jax.vjp(lambda x, g, b: _ln_dense(x, g, b, eps), x2d, gamma, beta)
    return vjp(dy)


fused_layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def use_pallas():
    """Kernel-override dispatch switch (OpKernelType.library analog)."""
    from ..flags import get_flag

    return get_flag("use_pallas")


# ---------------------------------------------------------------------------
# fused GRU sequence kernel (math/jit_kernel.h gru kernels + fused/fusion_gru
# analog): the hidden state lives in VMEM across ALL timesteps, so the
# recurrence reads/writes HBM once per sequence instead of once per step
# ---------------------------------------------------------------------------
def _gru_seq_kernel(x_ref, w_ref, h0_ref, len_ref, o_ref, *, hid, seq_len):
    w = w_ref[:].astype(jnp.float32)  # [H, 3H]
    w_uz = w[:, : 2 * hid]
    w_c = w[:, 2 * hid:]
    lens = len_ref[:].astype(jnp.int32).reshape(-1)  # [Bblk, 1] -> [Bblk]

    def step(t, h):
        xt = x_ref[:, t, :].astype(jnp.float32)  # [Bblk, 3H]
        gates = xt[:, : 2 * hid] + jax.lax.dot(
            h, w_uz, preferred_element_type=jnp.float32
        )
        u = jax.nn.sigmoid(gates[:, :hid])
        r = jax.nn.sigmoid(gates[:, hid:])
        c = jnp.tanh(
            xt[:, 2 * hid:]
            + jax.lax.dot(r * h, w_c, preferred_element_type=jnp.float32)
        )
        h_new = u * c + (1.0 - u) * h
        active = (t < lens)[:, None].astype(jnp.float32)
        h_new = active * h_new + (1.0 - active) * h
        o_ref[:, t, :] = h_new.astype(o_ref.dtype)
        return h_new

    jax.lax.fori_loop(0, seq_len, step, h0_ref[:].astype(jnp.float32))


def _gru_seq_fwd(xproj, w, h0, lens, block_b=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H3 = xproj.shape
    hid = H3 // 3
    _note("recurrent")
    block_b = _row_block(B, block_b)
    grid = (_cdiv(B, block_b),)
    return pl.pallas_call(
        functools.partial(_gru_seq_kernel, hid=hid, seq_len=T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, T, H3), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, H3), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, hid), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            # lens rides as [B, 1]: a 1D (block_b,) block is Mosaic-illegal
            # for block_b < 128; (block_b, 1) matches the array's last dim
            pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_b, T, hid), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
        interpret=_interpret(),
    )(xproj, w, h0, lens.reshape(B, 1))


def _gru_seq_dense(xproj, w, h0, lens):
    """Reference scan (also the recompute path for the backward pass)."""
    hid = xproj.shape[-1] // 3
    w_uz, w_c = w[:, : 2 * hid], w[:, 2 * hid:]

    def step(h, inp):
        xt, t = inp
        gates = xt[:, : 2 * hid] + h @ w_uz
        u = jax.nn.sigmoid(gates[:, :hid])
        r = jax.nn.sigmoid(gates[:, hid:])
        c = jnp.tanh(xt[:, 2 * hid:] + (r * h) @ w_c)
        h_new = u * c + (1.0 - u) * h
        act = (t < lens)[:, None].astype(h.dtype)
        h_new = act * h_new + (1 - act) * h
        return h_new, h_new

    xs = jnp.swapaxes(xproj, 0, 1)
    ts = jnp.arange(xproj.shape[1])
    _, hs = jax.lax.scan(step, h0, (xs, ts))
    return jnp.swapaxes(hs, 0, 1)


@jax.custom_vjp
def fused_gru(xproj, w, h0, lens):
    """VMEM-resident GRU over padded [B, T, 3H] projected inputs."""
    return _gru_seq_fwd(xproj, w, h0, lens)


def _gru_vjp_fwd(xproj, w, h0, lens):
    return _gru_seq_fwd(xproj, w, h0, lens), (xproj, w, h0, lens)


def _gru_vjp_bwd(res, dy):
    xproj, w, h0, lens = res
    _, vjp = jax.vjp(lambda x, w_, h_: _gru_seq_dense(x, w_, h_, lens),
                     xproj, w, h0)
    dx, dw, dh0 = vjp(dy)
    return dx, dw, dh0, None


fused_gru.defvjp(_gru_vjp_fwd, _gru_vjp_bwd)


# ---------------------------------------------------------------------------
# fused LSTM sequence kernel (math/jit_kernel.h lstm kernels +
# fused/fusion_lstm analog): hidden AND cell state live in VMEM across all
# timesteps — one HBM read of the projected gates and one write of each
# output sequence per batch block, instead of per-step round trips
# ---------------------------------------------------------------------------
def _lstm_seq_kernel(x_ref, w_ref, h0_ref, c0_ref, len_ref, o_ref, cell_ref,
                     *, hid, seq_len):
    w = w_ref[:].astype(jnp.float32)  # [H, 4H]
    lens = len_ref[:].astype(jnp.int32).reshape(-1)  # [Bblk, 1] -> [Bblk]

    def step(t, hc):
        h, c = hc
        xt = x_ref[:, t, :].astype(jnp.float32)  # [Bblk, 4H]
        gates = xt + jax.lax.dot(h, w, preferred_element_type=jnp.float32)
        # gate order i|f|c_hat|o (lstm_op.cc / _lstm_cell layout)
        i = jax.nn.sigmoid(gates[:, :hid])
        f = jax.nn.sigmoid(gates[:, hid: 2 * hid])
        c_hat = jnp.tanh(gates[:, 2 * hid: 3 * hid])
        o = jax.nn.sigmoid(gates[:, 3 * hid:])
        c_new = f * c + i * c_hat
        h_new = o * jnp.tanh(c_new)
        active = (t < lens)[:, None].astype(jnp.float32)
        c_new = active * c_new + (1.0 - active) * c
        h_new = active * h_new + (1.0 - active) * h
        o_ref[:, t, :] = h_new.astype(o_ref.dtype)
        cell_ref[:, t, :] = c_new.astype(cell_ref.dtype)
        return (h_new, c_new)

    jax.lax.fori_loop(
        0, seq_len, step,
        (h0_ref[:].astype(jnp.float32), c0_ref[:].astype(jnp.float32)),
    )


def _lstm_seq_fwd(xproj, w, h0, c0, lens, block_b=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H4 = xproj.shape
    hid = H4 // 4
    _note("recurrent")
    block_b = _row_block(B, block_b)
    grid = (_cdiv(B, block_b),)
    state_spec = pl.BlockSpec((block_b, hid), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    seq_spec = pl.BlockSpec((block_b, T, hid), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_lstm_seq_kernel, hid=hid, seq_len=T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, T, H4), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, H4), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            state_spec,
            state_spec,
            # lens rides as [B, 1] (1D sub-128 blocks are Mosaic-illegal)
            pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[seq_spec, seq_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
            jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
        ],
        interpret=_interpret(),
    )(xproj, w, h0, c0, lens.reshape(B, 1))


def _lstm_seq_dense(xproj, w, h0, c0, lens):
    """Reference scan (also the recompute path for the backward pass).
    Reuses nn_ops._lstm_cell — one copy of the gate math outside the
    hand-tiled kernel (which must slice refs explicitly)."""
    from .nn_ops import _lstm_cell  # lazy: nn_ops imports this module

    def step(carry, inp):
        h, c = carry
        xt, t = inp
        gates = xt + h @ w
        c_new, h_new = _lstm_cell(c, h, gates)
        act = (t < lens)[:, None].astype(h.dtype)
        c_new = act * c_new + (1 - act) * c
        h_new = act * h_new + (1 - act) * h
        return (h_new, c_new), (h_new, c_new)

    xs = jnp.swapaxes(xproj, 0, 1)
    ts = jnp.arange(xproj.shape[1])
    _, (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, ts))
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


@jax.custom_vjp
def fused_lstm(xproj, w, h0, c0, lens):
    """VMEM-resident LSTM over padded [B, T, 4H] projected inputs;
    returns (hidden_seq, cell_seq), each [B, T, H]."""
    return _lstm_seq_fwd(xproj, w, h0, c0, lens)


def _lstm_vjp_fwd(xproj, w, h0, c0, lens):
    return _lstm_seq_fwd(xproj, w, h0, c0, lens), (xproj, w, h0, c0, lens)


def _lstm_vjp_bwd(res, dy):
    xproj, w, h0, c0, lens = res
    _, vjp = jax.vjp(
        lambda x, w_, h_, c_: _lstm_seq_dense(x, w_, h_, c_, lens),
        xproj, w, h0, c0,
    )
    dx, dw, dh0, dc0 = vjp(dy)
    return dx, dw, dh0, dc0, None


fused_lstm.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)


# ---------------------------------------------------------------------------
# fused softmax cross entropy (row-blocked logsumexp + label gather; the
# backward is the analytic softmax(x) - onehot, no recompute needed)
# ---------------------------------------------------------------------------
def _sxent_kernel(x_ref, lbl_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)  # [Bblk, C]
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    lbl = lbl_ref[:].astype(jnp.int32).reshape(-1)  # [Bblk, 1] -> [Bblk]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    gold = jnp.sum(jnp.where(cols == lbl[:, None], x, 0.0), axis=-1,
                   keepdims=True)
    o_ref[:] = (lse - gold).astype(o_ref.dtype)


def _sxent_fwd_call(logits, labels, block_rows=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = logits.shape
    if block_rows is None:
        block_rows = _tuned(
            "softmax_xent", [logits.shape], logits.dtype,
            _row_block_candidates(R),
            {"block_rows": _row_block(R, 512)},
            build=lambda p: (lambda lg, lb: _sxent_fwd_call(
                lg, lb, p["block_rows"])),
            arg_specs=[(logits.shape, logits.dtype),
                       ((R,), "int32")],
        )["block_rows"]
    _note("xent")
    block_rows = _row_block(R, block_rows)
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        _sxent_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            # labels ride as [R, 1] (1D sub-128 blocks are Mosaic-illegal)
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.float32),
        interpret=_interpret(),
    )(logits, labels.reshape(R, 1))


def _sxent_validate(logits, labels):
    """Loud shape contract: 2-D logits + one int label per row.  A
    mis-shaped labels array used to broadcast through the gather
    (plausible wrong losses); now it raises at trace time."""
    if logits.ndim != 2:
        raise ValueError(
            "fused_softmax_xent: logits must be 2-D [rows, classes], got "
            "shape %s — reshape leading dims into rows first"
            % (tuple(logits.shape),))
    lbl_n = int(np.prod(labels.shape)) if labels.ndim else 0
    if labels.ndim > 2 or lbl_n != int(logits.shape[0]) or (
            labels.ndim == 2 and labels.shape[1] != 1):
        raise ValueError(
            "fused_softmax_xent: labels must be [rows]=%d (or [rows, 1]) "
            "ints, got shape %s — a mismatched labels array would "
            "mis-broadcast against the row blocks"
            % (int(logits.shape[0]), tuple(labels.shape)))
    if not jnp.issubdtype(labels.dtype, jnp.integer):
        raise ValueError(
            "fused_softmax_xent: labels must be integers, got %s"
            % labels.dtype)


def _sxent_bwd_kernel(x_ref, lbl_ref, dy_ref, dx_ref):
    """Row-blocked analytic backward: dx = (softmax(x) - onehot) * dy.
    The one-hot is an iota compare inside the tile — no [R, C] one-hot
    (or separately materialized softmax) array in HBM; dx is the
    gradient itself and unavoidable."""
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    lbl = lbl_ref[:].astype(jnp.int32).reshape(-1)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == lbl[:, None]).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * dy_ref[:].astype(jnp.float32)).astype(
        dx_ref.dtype)


def _sxent_bwd_call(logits, labels, dy, block_rows=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = logits.shape
    if block_rows is None:
        block_rows = _tuned(
            "softmax_xent_bwd", [logits.shape], logits.dtype,
            _row_block_candidates(R),
            {"block_rows": _row_block(R, 512)},
            build=lambda p: (lambda lg, lb, g: _sxent_bwd_call(
                lg, lb, g, p["block_rows"])),
            arg_specs=[(logits.shape, logits.dtype), ((R,), "int32"),
                       ((R, 1), "float32")],
        )["block_rows"]
    block_rows = _row_block(R, block_rows)
    row_spec = pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _sxent_bwd_kernel,
        grid=(_cdiv(R, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            row_spec,
            row_spec,
        ],
        out_specs=pl.BlockSpec((block_rows, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, C), logits.dtype),
        interpret=_interpret(),
    )(logits, labels.reshape(R, 1), dy.reshape(R, 1).astype(jnp.float32))


@jax.custom_vjp
def fused_softmax_xent(logits, labels):
    """Per-row -log softmax[label] over [rows, classes] + int labels [rows]."""
    _sxent_validate(logits, labels)
    return _sxent_fwd_call(logits, labels)


def _sxent_vjp_fwd(logits, labels):
    _sxent_validate(logits, labels)
    return _sxent_fwd_call(logits, labels), (logits, labels)


def _sxent_vjp_bwd(res, dy):
    logits, labels = res
    # blocked kernel backward (the dense softmax + one_hot pair this used
    # to materialize was 2x the [R, C] traffic of the gradient itself)
    return _sxent_bwd_call(logits, labels.reshape(-1), dy), None


fused_softmax_xent.defvjp(_sxent_vjp_fwd, _sxent_vjp_bwd)


# ---------------------------------------------------------------------------
# matmul-epilogue fusions (TPP-style primitive kernels, ROADMAP item 1):
# a blocked [M, K] @ [K, N] with the bias add + activation (or the SwiGLU
# gate product) applied to the accumulator TILE in VMEM before it ever
# reaches HBM — the XLA form writes the pre-activation [M, N] out and
# reads it back per epilogue op.  Grid (nm, nn), full-K per tile (the
# bench shapes keep K = d_model-ish, so an x/w tile pair fits VMEM
# comfortably); dots consume the input dtype (bf16 under AMP runs the
# MXU at full rate) and accumulate f32.  Backwards recompute through the
# dense reference (plain MXU matmuls — nothing to hand-fuse there).
# ---------------------------------------------------------------------------
_MM_ACTS = ("", "identity", "relu", "tanh", "sigmoid", "gelu", "swish")


def _mm_act(z, act):
    """f32 epilogue activation (exact erf gelu / beta-1 swish: the same
    defaults as the op lowerings in math_ops.ACTIVATIONS)."""
    if act in ("", "identity"):
        return z
    if act == "relu":
        return jnp.maximum(z, 0.0)
    if act == "tanh":
        return jnp.tanh(z)
    if act == "sigmoid":
        return jax.nn.sigmoid(z)
    if act == "gelu":
        return jax.nn.gelu(z, approximate=False)
    if act == "swish":
        return z * jax.nn.sigmoid(z)
    raise ValueError("matmul epilogue: unsupported activation %r" % (act,))


def _mm_kernel(*refs, act, has_bias):
    x_ref, w_ref = refs[0], refs[1]
    b_ref = refs[2] if has_bias else None
    o_ref = refs[-1]
    z = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    if has_bias:
        z = z + b_ref[:].astype(jnp.float32)  # [1, bn] broadcast
    o_ref[:] = _mm_act(z, act).astype(o_ref.dtype)


def _mm_col_block(n, default):
    """Lane-dim tiling: a multiple of 128 dividing n, else the full dim
    (a full minor-dim block is always Mosaic-legal)."""
    blk = min(default, n)
    if n % 128 == 0 and blk % 128 == 0 and n % blk == 0:
        return blk
    return n


def _mm_blocks(M, K, N, dtype, kernel, extra_w=1):
    """Tuned (block_m, block_n) for an [M, K] @ [K, N] epilogue kernel;
    extra_w doubles the per-tile weight footprint (SwiGLU reads two)."""
    cands = []
    for bm in (128, 256, 512):
        if M % bm:
            continue
        for bn in (128, 256, 512):
            if N % bn or bn % 128:
                continue
            if _mm_vmem_ok(M, K, N, bm, bn, extra_w):
                cands.append({"block_m": bm, "block_n": bn})
    default = {"block_m": _row_block(M, 256), "block_n": _mm_col_block(N, 256)}
    if extra_w == 2:
        # measure the kernel actually being tuned: SwiGLU runs two dots
        # plus the gate against each x tile — a plain-matmul timing
        # would rank candidates by the wrong weight traffic
        build = lambda p: (lambda x, wg, wu: _swiglu_call(
            x, wg, wu, p["block_m"], p["block_n"]))
        arg_specs = [((M, K), dtype), ((K, N), dtype), ((K, N), dtype)]
    else:
        build = lambda p: (lambda x, w: _mm_call(
            x, w, None, "", p["block_m"], p["block_n"]))
        arg_specs = [((M, K), dtype), ((K, N), dtype)]
    params = _tuned(
        kernel, [(M, K), (K, N)], dtype, cands, default,
        build=build, arg_specs=arg_specs,
    )
    bm = _row_block(M, params["block_m"])
    bn = _mm_col_block(N, params["block_n"])
    return bm, bn


def _mm_vmem_ok(M, K, N, bm, bn, extra_w=1):
    """x/w/out tiles (f32 upper bound) must sit well inside VMEM."""
    tile = (bm * K + extra_w * K * bn + 2 * bm * bn + bn) * 4
    return tile < 12 * 2 ** 20


def mm_epilogue_ok(M, K, N, act="", extra_w=1):
    """THE dispatch gate for the matmul-epilogue kernels (fc /
    fused_swiglu lowerings call this instead of re-deriving tiling
    policy): activation supported and the heuristic DEFAULT tile pair
    fits VMEM — tuned candidates are themselves VMEM-filtered in
    _mm_blocks, so a True here can never select a tile the kernel
    rejects."""
    return (act in _MM_ACTS
            and _mm_vmem_ok(M, K, N, _row_block(M, 256),
                            _mm_col_block(N, 256), extra_w))


def _mm_call(x2d, w, bias, act, block_m, block_n):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x2d.shape
    N = w.shape[1]
    _note("matmul_epilogue")
    grid = (_cdiv(M, block_m), _cdiv(N, block_n))
    in_specs = [
        pl.BlockSpec((block_m, K), lambda i, j: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((K, block_n), lambda i, j: (0, j),
                     memory_space=pltpu.VMEM),
    ]
    args = [x2d, w]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, block_n), lambda i, j: (0, j),
                                     memory_space=pltpu.VMEM))
        args.append(bias.reshape(1, N))
    return pl.pallas_call(
        functools.partial(_mm_kernel, act=act, has_bias=bias is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        interpret=_interpret(),
    )(*args)


def _mm_dense(x2d, w, bias, act):
    """XLA reference (also the backward recompute path)."""
    z = jnp.dot(x2d, w, preferred_element_type=jnp.float32)
    if bias is not None:
        z = z + bias.reshape(1, -1).astype(jnp.float32)
    return _mm_act(z, act).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def matmul_bias_act(x2d, w, bias=None, act="", block_m=None, block_n=None):
    """Blocked matmul with fused bias + activation epilogue over
    [M, K] @ [K, N] (+ bias [N]); act in "", relu, tanh, sigmoid, gelu
    (exact erf), swish.  block_m/block_n default to the tuning cache's
    decision for this shape bucket."""
    if block_m is None or block_n is None:
        block_m, block_n = _mm_blocks(x2d.shape[0], x2d.shape[1],
                                      w.shape[1], x2d.dtype, "matmul_bias_act")
    return _mm_call(x2d, w, bias, act, block_m, block_n)


def _mm_vjp_fwd(x2d, w, bias, act, block_m, block_n):
    return (matmul_bias_act(x2d, w, bias, act, block_m, block_n),
            (x2d, w, bias))


def _mm_vjp_bwd(act, block_m, block_n, res, dy):
    x2d, w, bias = res
    if bias is None:
        _, vjp = jax.vjp(lambda x, w_: _mm_dense(x, w_, None, act), x2d, w)
        dx, dw = vjp(dy)
        return dx, dw, None
    _, vjp = jax.vjp(lambda x, w_, b: _mm_dense(x, w_, b, act), x2d, w, bias)
    return vjp(dy)


matmul_bias_act.defvjp(_mm_vjp_fwd, _mm_vjp_bwd)


def _swiglu_kernel(x_ref, wg_ref, wu_ref, o_ref):
    x = x_ref[:]
    g = jnp.dot(x, wg_ref[:], preferred_element_type=jnp.float32)
    u = jnp.dot(x, wu_ref[:], preferred_element_type=jnp.float32)
    o_ref[:] = (g * jax.nn.sigmoid(g) * u).astype(o_ref.dtype)


def _swiglu_call(x2d, wg, wu, block_m, block_n):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x2d.shape
    N = wg.shape[1]
    _note("matmul_epilogue")
    w_spec = pl.BlockSpec((K, block_n), lambda i, j: (0, j),
                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _swiglu_kernel,
        grid=(_cdiv(M, block_m), _cdiv(N, block_n)),
        in_specs=[
            pl.BlockSpec((block_m, K), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            w_spec,
            w_spec,
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        interpret=_interpret(),
    )(x2d, wg, wu)


def _swiglu_dense(x2d, wg, wu):
    g = jnp.dot(x2d, wg, preferred_element_type=jnp.float32)
    u = jnp.dot(x2d, wu, preferred_element_type=jnp.float32)
    return (g * jax.nn.sigmoid(g) * u).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def matmul_swiglu(x2d, wg, wu, block_m=None, block_n=None):
    """Fused SwiGLU gating: silu(x @ wg) * (x @ wu) over [M, K] with
    wg/wu [K, N].  BOTH projections of a tile and the gate product
    happen against one resident x tile — the gate/up pre-activations
    never exist in HBM (the unfused form writes and re-reads both)."""
    if block_m is None or block_n is None:
        block_m, block_n = _mm_blocks(x2d.shape[0], x2d.shape[1],
                                      wg.shape[1], x2d.dtype,
                                      "matmul_swiglu", extra_w=2)
    return _swiglu_call(x2d, wg, wu, block_m, block_n)


def _swiglu_vjp_fwd(x2d, wg, wu, block_m, block_n):
    return matmul_swiglu(x2d, wg, wu, block_m, block_n), (x2d, wg, wu)


def _swiglu_vjp_bwd(block_m, block_n, res, dy):
    x2d, wg, wu = res
    _, vjp = jax.vjp(_swiglu_dense, x2d, wg, wu)
    return vjp(dy)


matmul_swiglu.defvjp(_swiglu_vjp_fwd, _swiglu_vjp_bwd)


# ---------------------------------------------------------------------------
# residual-add + layer norm: the transformer pre/post-process pair
# (x + sublayer -> LN) with the add as the LN kernel's PROLOGUE — the sum
# is formed on the row tile already in VMEM, normalized in the same pass,
# and both the sum (the residual stream the next block reads) and the
# normalized output write out once.
# ---------------------------------------------------------------------------
def _add_ln_kernel(x_ref, y_ref, g_ref, b_ref, s_ref, o_ref, *, eps):
    s = x_ref[:].astype(jnp.float32) + y_ref[:].astype(jnp.float32)
    s_ref[:] = s.astype(s_ref.dtype)
    mean = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mean), axis=-1, keepdims=True)
    yn = (s - mean) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (yn * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _add_ln_call(x2d, y2d, gamma, beta, eps, block_rows):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    _note("layernorm")
    block_rows = _row_block(R, block_rows)
    row_spec = pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_add_ln_kernel, eps=eps),
        grid=(_cdiv(R, block_rows),),
        in_specs=[row_spec, row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), x2d.dtype),
            jax.ShapeDtypeStruct((R, H), x2d.dtype),
        ],
        interpret=_interpret(),
    )(x2d, y2d, gamma, beta)


def _add_ln_dense(x2d, y2d, gamma, beta, eps):
    s = x2d.astype(jnp.float32) + y2d.astype(jnp.float32)
    mean = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mean), axis=-1, keepdims=True)
    yn = (s - mean) * jax.lax.rsqrt(var + eps)
    return (s.astype(x2d.dtype),
            (yn * gamma + beta).astype(x2d.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_add_layer_norm(x2d, y2d, gamma, beta, eps=1e-5, block_rows=None):
    """Residual add + row layer norm over [rows, hidden]; returns
    (sum, normalized) — the sum IS the residual stream, so callers that
    need it downstream read the fused op's first output instead of
    keeping a separate add.  An explicit `block_rows` skips the tuning
    search (shard_map bodies pin deterministic per-shard blocks)."""
    R, H = x2d.shape
    if block_rows is None:
        block_rows = _tuned(
            "add_layer_norm", [x2d.shape], x2d.dtype,
            _row_block_candidates(R),
            {"block_rows": _row_block(R, 256)},
            build=lambda p: (lambda x, y, g, b: _add_ln_call(
                x, y, g, b, eps, p["block_rows"])),
            arg_specs=[(x2d.shape, x2d.dtype)] * 2
            + [(gamma.shape, gamma.dtype), (beta.shape, beta.dtype)],
        )["block_rows"]
    return _add_ln_call(x2d, y2d, gamma, beta, eps, block_rows)


def _add_ln_vjp_fwd(x2d, y2d, gamma, beta, eps, block_rows):
    return (fused_add_layer_norm(x2d, y2d, gamma, beta, eps, block_rows),
            (x2d, y2d, gamma, beta))


def _add_ln_vjp_bwd(eps, _block_rows, res, cts):
    x2d, y2d, gamma, beta = res
    _, vjp = jax.vjp(
        lambda x, y, g, b: _add_ln_dense(x, y, g, b, eps),
        x2d, y2d, gamma, beta)
    return vjp(cts)


fused_add_layer_norm.defvjp(_add_ln_vjp_fwd, _add_ln_vjp_bwd)


# ---------------------------------------------------------------------------
# logits-free fused cross entropy: the final [H, V] projection fused INTO
# the loss.  Forward streams V in block_v-sized tiles — each tile's
# logits exist only as a VMEM [block_r, block_v] accumulator feeding an
# online logsumexp (flash-attention's trick applied to the vocab axis),
# the gold logit gather, and the row logit-sum (label smoothing's mean
# term) — so the [R, V] f32 logits tensor NEVER materializes in HBM (at
# transformer-base bench config that is a 1.3 GB write + read per step
# direction, plus its gradient twin).  Backward recomputes each tile's
# softmax from the saved per-row lse and contracts in-kernel: dx
# accumulates g @ w_tile^T across the v grid, dw writes one [H, block_v]
# tile per v index accumulated across row blocks.  The vocab axis is
# masked in-kernel (cols >= V contribute nothing), so ragged vocab sizes
# (10000 / 30522 / 50257) need no padding copy of w.
# ---------------------------------------------------------------------------
def _lxent_fwd_kernel(x_ref, w_ref, lbl_ref, loss_ref, lse_ref,
                      m_ref, l_ref, gold_ref, sum_ref,
                      *, block_v, nv, vocab, eps):
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        gold_ref[:] = jnp.zeros_like(gold_ref)
        sum_ref[:] = jnp.zeros_like(sum_ref)

    cols = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_v), 1)  # global vocab columns of this tile
    vmask = cols < vocab
    # zero the out-of-vocab tail of the weight tile BEFORE the dot: the
    # last block may read past [H, V] (padded garbage on-chip)
    w = jnp.where(vmask, w_ref[:], 0.0)
    z = jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)
    lbl = lbl_ref[:].astype(jnp.int32).reshape(-1)  # [br]
    gold_ref[:] += jnp.sum(
        jnp.where(cols == lbl[:, None], z, 0.0), axis=1, keepdims=True)
    sum_ref[:] += jnp.sum(jnp.where(vmask, z, 0.0), axis=1, keepdims=True)
    zm = jnp.where(vmask, z, NEG_INF)
    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(zm, axis=1, keepdims=True))
    l_ref[:] = (l_ref[:] * jnp.exp(m_prev - m_new)
                + jnp.sum(jnp.exp(zm - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new

    @pl.when(vi == nv - 1)
    def _write():
        lse = m_ref[:] + jnp.log(l_ref[:])
        lbl_f = lbl_ref[:].astype(jnp.int32)
        valid = ((lbl_f >= 0) & (lbl_f < vocab)).astype(jnp.float32)
        loss = valid * (1.0 - eps) * (lse - gold_ref[:])
        if eps:
            loss = loss + eps * (lse - sum_ref[:] / vocab)
        loss_ref[:] = loss
        lse_ref[:] = lse


def _lxent_grad_tile(x, w, lbl, lse, dy, vi, block_v, vocab, eps,
                     valid=None, vocab_total=None):
    """Shared backward tile math: g = dy * d loss / d z for this
    [br, block_v] logits tile, recomputed from the saved lse.  The
    vocab-SHARDED form passes `valid` (row validity against the GLOBAL
    vocab — local label coords can't derive it) and `vocab_total` (the
    smoothing denominator spans every shard's columns)."""
    cols = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_v), 1)
    vmask = cols < vocab
    w = jnp.where(vmask, w, 0.0)
    z = jnp.dot(x, w, preferred_element_type=jnp.float32)
    p = jnp.where(vmask, jnp.exp(z - lse), 0.0)
    lbl = lbl.astype(jnp.int32).reshape(-1)
    onehot = (cols == lbl[:, None]).astype(jnp.float32)
    if valid is None:
        valid = ((lbl >= 0) & (lbl < vocab)).astype(jnp.float32)[:, None]
    g = valid * (1.0 - eps) * (p - onehot)
    if eps:
        g = g + eps * (p - jnp.where(
            vmask, 1.0 / (vocab_total or vocab), 0.0))
    return g * dy, w


def _lxent_dx_kernel(x_ref, w_ref, lbl_ref, lse_ref, dy_ref, dx_ref,
                     dx_acc, *, block_v, nv, vocab, eps):
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        dx_acc[:] = jnp.zeros_like(dx_acc)

    g, w = _lxent_grad_tile(
        x_ref[:], w_ref[:], lbl_ref[:], lse_ref[:].astype(jnp.float32),
        dy_ref[:].astype(jnp.float32), vi, block_v, vocab, eps)
    dx_acc[:] += jnp.dot(g.astype(x_ref.dtype), w.T,
                         preferred_element_type=jnp.float32)

    @pl.when(vi == nv - 1)
    def _write():
        dx_ref[:] = dx_acc[:].astype(dx_ref.dtype)


def _lxent_dw_kernel(x_ref, w_ref, lbl_ref, lse_ref, dy_ref, dw_ref,
                     dw_acc, *, block_v, nr, vocab, rows, eps):
    from jax.experimental import pallas as pl

    vi = pl.program_id(0)  # this grid is (nv, nr) — v is OUTER
    ri = pl.program_id(1)

    @pl.when(ri == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)

    g, _w = _lxent_grad_tile(
        x_ref[:], w_ref[:], lbl_ref[:], lse_ref[:].astype(jnp.float32),
        dy_ref[:].astype(jnp.float32), vi, block_v, vocab, eps)
    # unlike loss/dx (whose padded-row outputs are simply discarded),
    # dw SUMS over row tiles — zero the tail tile's out-of-range rows
    # on BOTH dot operands before they reach the accumulator (block_r
    # need not divide R; padded x rows can be NaN, and NaN * 0 = NaN)
    br = g.shape[0]
    rr = ri * br + jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0)
    rmask = rr < rows
    g = jnp.where(rmask, g, 0.0)
    xt = jnp.where(rmask, x_ref[:], 0)
    dw_acc[:] += jnp.dot(xt.T, g.astype(x_ref.dtype),
                         preferred_element_type=jnp.float32)

    @pl.when(ri == nr - 1)
    def _write():
        dw_ref[:] = dw_acc[:].astype(dw_ref.dtype)


def _lx_vmem_ok(H, br, bv):
    """Worst-pass (dw) resident f32 upper bound: the x row tile
    [br, H], the w input + dw output + dw_acc scratch tiles [H, bv]
    each, and the recomputed logits/softmax tile [br, bv] must sit
    well inside VMEM — the linear-xent twin of _mm_vmem_ok (same
    12 MB line)."""
    tile = (br * H + 3 * H * bv + 2 * br * bv) * 4
    return tile < 12 * 2 ** 20


def _lxent_default_blocks(R, H, V):
    """The deterministic (block_r, block_v) seed — also the FIXED
    choice inside shard_map (a per-shard tuning search there would
    attribute collective time to block sizes, the qvec precedent)."""
    br0 = _row_block(R, 256)
    bv0 = min(V, 1024 if V % 128 == 0 else 2048)
    # shrink the seeded default until the dw pass fits VMEM (consult-
    # only regimes dispatch it unvalidated); halving keeps bv0 a
    # multiple of 128 (Mosaic minor-dim rule) — a small non-multiple
    # bv0 == V full-dim block can't legally shrink and stays put
    while bv0 % 256 == 0 and bv0 > 128 and not _lx_vmem_ok(H, br0, bv0):
        bv0 //= 2
    return br0, bv0


def _lxent_blocks(R, H, V, dtype):
    cands = []
    for br in (128, 256, 512):
        if R % br:
            continue
        for bv in (512, 1024, 2048):
            if _lx_vmem_ok(H, br, bv):
                cands.append({"block_r": br, "block_v": bv})
    br0, bv0 = _lxent_default_blocks(R, H, V)
    default = {"block_r": br0, "block_v": bv0}
    params = _tuned(
        "linear_xent", [(R, H), (H, V)], dtype, cands, default,
        build=lambda p: (lambda x, w, lb: _lxent_fwd(
            x, w, lb, 0.0, p["block_r"], p["block_v"])),
        arg_specs=[((R, H), dtype), ((H, V), dtype), ((R,), "int32")],
    )
    return _row_block(R, params["block_r"]), int(params["block_v"])


def _lxent_specs(block_r, block_v, H, dw_grid=False):
    """(x, w, row...) BlockSpecs; dw_grid flips which grid axis indexes
    rows vs vocab tiles ((b, vi, ri) instead of (b-less) (ri, vi))."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if dw_grid:
        x_spec = pl.BlockSpec((block_r, H), lambda i, j: (j, 0),
                              memory_space=pltpu.VMEM)
        w_spec = pl.BlockSpec((H, block_v), lambda i, j: (0, i),
                              memory_space=pltpu.VMEM)
        row_spec = pl.BlockSpec((block_r, 1), lambda i, j: (j, 0),
                                memory_space=pltpu.VMEM)
    else:
        x_spec = pl.BlockSpec((block_r, H), lambda i, j: (i, 0),
                              memory_space=pltpu.VMEM)
        w_spec = pl.BlockSpec((H, block_v), lambda i, j: (0, j),
                              memory_space=pltpu.VMEM)
        row_spec = pl.BlockSpec((block_r, 1), lambda i, j: (i, 0),
                                memory_space=pltpu.VMEM)
    return x_spec, w_spec, row_spec


def _lxent_fwd(x2d, w, labels, eps, block_r, block_v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    V = w.shape[1]
    _note("xent")
    nr, nv = _cdiv(R, block_r), _cdiv(V, block_v)
    x_spec, w_spec, row_spec = _lxent_specs(block_r, block_v, H)
    loss, lse = pl.pallas_call(
        functools.partial(_lxent_fwd_kernel, block_v=block_v, nv=nv,
                          vocab=V, eps=float(eps)),
        grid=(nr, nv),
        in_specs=[x_spec, w_spec, row_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_r, 1), jnp.float32),
            pltpu.VMEM((block_r, 1), jnp.float32),
            pltpu.VMEM((block_r, 1), jnp.float32),
            pltpu.VMEM((block_r, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, w, labels.astype(jnp.int32).reshape(R, 1))
    return loss, lse


def _lxent_bwd(x2d, w, labels, lse, dy, eps, block_r, block_v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    V = w.shape[1]
    nr, nv = _cdiv(R, block_r), _cdiv(V, block_v)
    lbl = labels.astype(jnp.int32).reshape(R, 1)
    lse2 = lse.reshape(R, 1)
    dy2 = dy.reshape(R, 1).astype(jnp.float32)

    x_spec, w_spec, row_spec = _lxent_specs(block_r, block_v, H)
    dx = pl.pallas_call(
        functools.partial(_lxent_dx_kernel, block_v=block_v, nv=nv,
                          vocab=V, eps=float(eps)),
        grid=(nr, nv),
        in_specs=[x_spec, w_spec, row_spec, row_spec, row_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((R, H), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((block_r, H), jnp.float32)],
        interpret=_interpret(),
    )(x2d, w, lbl, lse2, dy2)

    x_spec, w_spec, row_spec = _lxent_specs(block_r, block_v, H,
                                            dw_grid=True)
    dw = pl.pallas_call(
        functools.partial(_lxent_dw_kernel, block_v=block_v, nr=nr,
                          vocab=V, rows=R, eps=float(eps)),
        grid=(nv, nr),
        in_specs=[x_spec, w_spec, row_spec, row_spec, row_spec],
        out_specs=w_spec,
        out_shape=jax.ShapeDtypeStruct((H, V), w.dtype),
        scratch_shapes=[pltpu.VMEM((H, block_v), jnp.float32)],
        interpret=_interpret(),
    )(x2d, w, lbl, lse2, dy2)
    return dx, dw


def _linear_xent_dense(x2d, w, labels, eps=0.0):
    """XLA reference: materializes the [R, V] logits (tests + the
    non-pallas fallback).  Same label convention as the kernel and
    smooth_label_xent: out-of-range labels contribute the smoothing
    term only."""
    lg = jnp.dot(x2d, w, preferred_element_type=jnp.float32)
    v = lg.shape[-1]
    lse = jax.scipy.special.logsumexp(lg, axis=-1, keepdims=True)
    lbl = labels.astype(jnp.int32).reshape(-1)
    onehot_gold = jnp.sum(
        jnp.where(jnp.arange(v)[None, :] == lbl[:, None], lg, 0.0),
        axis=-1, keepdims=True)
    valid = ((lbl >= 0) & (lbl < v))[:, None]
    loss = jnp.where(valid, (1.0 - eps) * (lse - onehot_gold), 0.0)
    if eps:
        loss = loss + eps * (lse - jnp.mean(lg, axis=-1, keepdims=True))
    return loss


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_linear_xent(x2d, w, labels, eps=0.0, block_r=None, block_v=None):
    """Logits-free projected cross entropy: -log softmax(x @ w)[label]
    per row (label-smoothed by eps against the uniform prior), computed
    without the [R, V] logits array ever reaching HBM.  x2d [R, H],
    w [H, V], labels [R] int; returns [R, 1] f32 losses.  Out-of-range
    labels (pad ids) contribute the smoothing term only (the one_hot
    convention, matching smooth_label_xent)."""
    if block_r is None or block_v is None:
        block_r, block_v = _lxent_blocks(x2d.shape[0], x2d.shape[1],
                                         w.shape[1], x2d.dtype)
    loss, _lse = _lxent_fwd(x2d, w, labels, eps, block_r, block_v)
    return loss


def _lxent_vjp_fwd(x2d, w, labels, eps, block_r, block_v):
    if block_r is None or block_v is None:
        block_r, block_v = _lxent_blocks(x2d.shape[0], x2d.shape[1],
                                         w.shape[1], x2d.dtype)
    loss, lse = _lxent_fwd(x2d, w, labels, eps, block_r, block_v)
    return loss, (x2d, w, labels, lse, block_r, block_v)


def _lxent_vjp_bwd(eps, _block_r, _block_v, res, dy):
    x2d, w, labels, lse, block_r, block_v = res
    dx, dw = _lxent_bwd(x2d, w, labels, lse, dy, eps, block_r, block_v)
    dlbl = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dx, dw, dlbl


fused_linear_xent.defvjp(_lxent_vjp_fwd, _lxent_vjp_bwd)


# ---------------------------------------------------------------------------
# vocab-SHARDED linear xent: the per-shard body the spmd_epilogue layer
# runs inside shard_map when the rule table vocab-shards the projection
# (softmax_out.w / tied emb.w).  Each shard streams only its [H, V/n]
# weight slab; the online-logsumexp state that the unsharded kernel
# keeps per row across vocab TILES is here combined per row across
# vocab SHARDS with three scalar-per-row collectives (pmax/psum of
# lse/gold/sum) — the [R, V] logits still never exist anywhere, now not
# even per device.
# ---------------------------------------------------------------------------
def _lxent_parts_kernel(x_ref, w_ref, lbl_ref, lse_ref, gold_ref, sum_ref,
                        m_ref, l_ref, g_acc, s_acc, *, block_v, nv, vocab):
    """The fwd kernel's streaming pass with the LOSS ASSEMBLY removed:
    outputs the per-row (lse, gold, sum) partials of THIS vocab shard.
    `lbl` is in LOCAL column coords (label - shard_offset) — an
    out-of-shard label matches no real column, and a padded-tail column
    it might alias carries a zeroed weight, so gold accumulates 0."""
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        g_acc[:] = jnp.zeros_like(g_acc)
        s_acc[:] = jnp.zeros_like(s_acc)

    cols = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_v), 1)
    vmask = cols < vocab
    w = jnp.where(vmask, w_ref[:], 0.0)
    z = jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)
    lbl = lbl_ref[:].astype(jnp.int32).reshape(-1)
    g_acc[:] += jnp.sum(
        jnp.where(cols == lbl[:, None], z, 0.0), axis=1, keepdims=True)
    s_acc[:] += jnp.sum(jnp.where(vmask, z, 0.0), axis=1, keepdims=True)
    zm = jnp.where(vmask, z, NEG_INF)
    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(zm, axis=1, keepdims=True))
    l_ref[:] = (l_ref[:] * jnp.exp(m_prev - m_new)
                + jnp.sum(jnp.exp(zm - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new

    @pl.when(vi == nv - 1)
    def _write():
        lse_ref[:] = m_ref[:] + jnp.log(l_ref[:])
        gold_ref[:] = g_acc[:]
        sum_ref[:] = s_acc[:]


def _lxent_parts(x2d, w, lbl_local, block_r, block_v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    V = w.shape[1]
    _note("xent")
    nr, nv = _cdiv(R, block_r), _cdiv(V, block_v)
    x_spec, w_spec, row_spec = _lxent_specs(block_r, block_v, H)
    return pl.pallas_call(
        functools.partial(_lxent_parts_kernel, block_v=block_v, nv=nv,
                          vocab=V),
        grid=(nr, nv),
        in_specs=[x_spec, w_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((R, 1), jnp.float32)] * 3,
        scratch_shapes=[pltpu.VMEM((block_r, 1), jnp.float32)] * 4,
        interpret=_interpret(),
    )(x2d, w, lbl_local.astype(jnp.int32).reshape(R, 1))


def _lxent_dx_kernel_sharded(x_ref, w_ref, lbl_ref, vld_ref, lse_ref,
                             dy_ref, dx_ref, dx_acc,
                             *, block_v, nv, vocab, vocab_total, eps):
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        dx_acc[:] = jnp.zeros_like(dx_acc)

    g, w = _lxent_grad_tile(
        x_ref[:], w_ref[:], lbl_ref[:], lse_ref[:].astype(jnp.float32),
        dy_ref[:].astype(jnp.float32), vi, block_v, vocab, eps,
        valid=vld_ref[:].astype(jnp.float32), vocab_total=vocab_total)
    dx_acc[:] += jnp.dot(g.astype(x_ref.dtype), w.T,
                         preferred_element_type=jnp.float32)

    @pl.when(vi == nv - 1)
    def _write():
        dx_ref[:] = dx_acc[:].astype(dx_ref.dtype)


def _lxent_dw_kernel_sharded(x_ref, w_ref, lbl_ref, vld_ref, lse_ref,
                             dy_ref, dw_ref, dw_acc,
                             *, block_v, nr, vocab, vocab_total, rows, eps):
    from jax.experimental import pallas as pl

    vi = pl.program_id(0)
    ri = pl.program_id(1)

    @pl.when(ri == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)

    g, _w = _lxent_grad_tile(
        x_ref[:], w_ref[:], lbl_ref[:], lse_ref[:].astype(jnp.float32),
        dy_ref[:].astype(jnp.float32), vi, block_v, vocab, eps,
        valid=vld_ref[:].astype(jnp.float32), vocab_total=vocab_total)
    br = g.shape[0]
    rr = ri * br + jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0)
    rmask = rr < rows
    g = jnp.where(rmask, g, 0.0)
    xt = jnp.where(rmask, x_ref[:], 0)
    dw_acc[:] += jnp.dot(xt.T, g.astype(x_ref.dtype),
                         preferred_element_type=jnp.float32)

    @pl.when(ri == nr - 1)
    def _write():
        dw_ref[:] = dw_acc[:].astype(dw_ref.dtype)


def _lxent_bwd_sharded(x2d, w, lbl_local, vld, lse, dy, eps, vocab_total,
                       block_r, block_v):
    """(dx_partial, dw_local) for this vocab shard: dx sums only the
    local columns' contributions (the caller psums it over the vocab
    axis), dw is the full gradient of the local slab (the shard_map
    transpose psums it over any axis the weight is replicated on)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    V = w.shape[1]
    nr, nv = _cdiv(R, block_r), _cdiv(V, block_v)
    lbl = lbl_local.astype(jnp.int32).reshape(R, 1)
    vld2 = vld.reshape(R, 1).astype(jnp.float32)
    lse2 = lse.reshape(R, 1)
    dy2 = dy.reshape(R, 1).astype(jnp.float32)

    x_spec, w_spec, row_spec = _lxent_specs(block_r, block_v, H)
    dx = pl.pallas_call(
        functools.partial(_lxent_dx_kernel_sharded, block_v=block_v,
                          nv=nv, vocab=V, vocab_total=vocab_total,
                          eps=float(eps)),
        grid=(nr, nv),
        in_specs=[x_spec, w_spec, row_spec, row_spec, row_spec, row_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((R, H), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((block_r, H), jnp.float32)],
        interpret=_interpret(),
    )(x2d, w, lbl, vld2, lse2, dy2)

    x_spec, w_spec, row_spec = _lxent_specs(block_r, block_v, H,
                                            dw_grid=True)
    dw = pl.pallas_call(
        functools.partial(_lxent_dw_kernel_sharded, block_v=block_v,
                          nr=nr, vocab=V, vocab_total=vocab_total,
                          rows=R, eps=float(eps)),
        grid=(nv, nr),
        in_specs=[x_spec, w_spec, row_spec, row_spec, row_spec, row_spec],
        out_specs=w_spec,
        out_shape=jax.ShapeDtypeStruct((H, V), w.dtype),
        scratch_shapes=[pltpu.VMEM((H, block_v), jnp.float32)],
        interpret=_interpret(),
    )(x2d, w, lbl, vld2, lse2, dy2)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def sharded_linear_xent(x2d, w_local, labels, eps, axis, vocab_total,
                        block_r, block_v):
    """Per-shard linear xent over the vocab axis `axis` of a live
    shard_map: x2d [R, H] (this shard's rows), w_local [H, V/n] (this
    shard's vocab slab), labels [R] in GLOBAL vocab coords.  Collectives
    are per-row scalars only: pmax/psum combine each shard's online
    (lse, gold, sum) into the global loss, and the backward psums dx
    over the vocab shards.  Returns [R, 1] f32 losses on every shard."""
    loss, _res = _sharded_lxent_fwd(x2d, w_local, labels, eps, axis,
                                    vocab_total, block_r, block_v)
    return loss


def _sharded_lxent_fwd(x2d, w_local, labels, eps, axis, vocab_total,
                       block_r, block_v):
    R = x2d.shape[0]
    v_local = w_local.shape[1]
    col0 = jax.lax.axis_index(axis).astype(jnp.int32) * v_local
    lbl = labels.astype(jnp.int32).reshape(R)
    lbl_local = lbl - col0
    lse_j, gold_j, sum_j = _lxent_parts(x2d, w_local, lbl_local,
                                        block_r, block_v)
    m = jax.lax.pmax(lse_j, axis)
    lse = jnp.log(jax.lax.psum(jnp.exp(lse_j - m), axis)) + m
    gold = jax.lax.psum(gold_j, axis)
    sz = jax.lax.psum(sum_j, axis)
    valid = ((lbl >= 0) & (lbl < vocab_total)).astype(
        jnp.float32)[:, None]
    loss = valid * (1.0 - eps) * (lse - gold)
    if eps:
        loss = loss + eps * (lse - sz / vocab_total)
    return loss, (x2d, w_local, lbl_local, valid, lse)


def _sharded_lxent_vjp_fwd(x2d, w_local, labels, eps, axis, vocab_total,
                           block_r, block_v):
    return _sharded_lxent_fwd(x2d, w_local, labels, eps, axis,
                              vocab_total, block_r, block_v)


def _sharded_lxent_vjp_bwd(eps, axis, vocab_total, block_r, block_v,
                           res, dy):
    x2d, w_local, lbl_local, valid, lse = res
    # the loss leaves the enclosing shard_map through an out_spec that
    # does NOT mention the vocab axis: the transpose SPLITS the global
    # cotangent across the shards (only sum_j dy_j == dy is guaranteed).
    # The tile math needs the full dy on every shard — reconstitute it
    dy = jax.lax.psum(dy, axis)
    dx_p, dw = _lxent_bwd_sharded(x2d, w_local, lbl_local, valid, lse,
                                  dy, eps, vocab_total, block_r, block_v)
    # dx stays the PARTIAL sum of this shard's columns: x enters the
    # enclosing shard_map with the vocab axis unmentioned, and under
    # check_rep=False the shard_map transpose itself psums such inputs'
    # cotangents — an explicit psum here would double-count
    dlbl = np.zeros(lbl_local.shape, dtype=jax.dtypes.float0)
    return dx_p, dw, dlbl


sharded_linear_xent.defvjp(_sharded_lxent_vjp_fwd, _sharded_lxent_vjp_bwd)
