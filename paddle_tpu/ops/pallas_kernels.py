"""Pallas TPU kernels: the hand-fused hot-op layer.

Role parity with the reference's specialized kernel libraries — the cuDNN
kernel variants and operators/math/ JIT kernels (SURVEY §2.6 math/,
fused/) — but written for the TPU memory hierarchy: q-blocked
flash attention with online softmax (keeps the [T,T] score matrix out of
HBM) and a row-blocked fused layer_norm.  Backward passes use custom_vjp
with XLA-fused recompute (the standard memory-for-FLOPs trade on TPU).

Kernels run compiled on TPU and in interpreter mode elsewhere, so the same
code path is unit-testable on the CPU mesh.  Dispatch happens inside the
regular op lowerings when FLAGS_use_pallas is on (the analog of the
reference's OpKernelType.library_type kernel override).
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def _cdiv(a, b):
    return (a + b - 1) // b


def _row_block(n, default):
    """Shared row/batch tiling heuristic: the default block when it
    divides n, else the largest of (8, 1) that does."""
    blk = min(default, n)
    if n % blk != 0:
        blk = 1 if n % 8 else 8
    return blk


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, kb_ref, o_ref, *, block_k, causal,
                      scale, q_block):
    """One (batch*head, q_block) cell: online softmax over k blocks.
    q_ref: [bq, d]; k_ref/v_ref: [T, d] (whole sequence resident in VMEM);
    kb_ref: [1, T] additive key bias (the padding-mask row, broadcast over
    q rows — rank-1 in T so it never re-materializes the [T,T] scores)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # block refs: [1, bq, d]
    _, T, d = k_ref.shape
    bq = q.shape[0]
    nk = T // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        kb = kb_ref[0, 0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        s = s + kb[None, :]
        if causal:
            q_pos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, kbias, causal, scale, block_q, block_k):
    """q/k/v: [BH, T, d], kbias: [BH, T] additive key bias -> o [BH, T, d]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, d = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (
        "flash attention requires seq len %d divisible by block sizes "
        "(%d, %d) — pad the sequence" % (T, block_q, block_k)
    )
    grid = (BH, T // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_k=block_k,
        causal=causal,
        scale=scale,
        q_block=block_q,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, T), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, kbias.reshape(BH, 1, T))


def _dense_attention(q, k, v, causal, scale, kbias=None):
    """XLA reference implementation (used for the backward recompute)."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if kbias is not None:
        s = s + kbias[:, None, :].astype(jnp.float32)
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(q, k, v, kbias=None, causal=False, scale=None,
                    block_q=128, block_k=128):
    """Fused attention over [BH, T, d] (flash-style online softmax).
    kbias: optional [BH, T] additive key bias (padding mask row)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(q.shape[:2], jnp.float32)
    return _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k)


def _flash_vjp_fwd(q, k, v, kbias, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kb = kbias if kbias is not None else jnp.zeros(q.shape[:2], jnp.float32)
    o = _flash_fwd(q, k, v, kb, causal, scale, block_q, block_k)
    return o, (q, k, v, kbias)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, kbias = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # recompute-based backward: XLA fuses the re-derived softmax with the
    # grad matmuls; trades FLOPs for never materializing fwd residuals
    if kbias is None:
        _, vjp = jax.vjp(
            lambda q, k, v: _dense_attention(q, k, v, causal, scale), q, k, v
        )
        return vjp(do) + (None,)
    _, vjp = jax.vjp(
        lambda q, k, v, kb: _dense_attention(q, k, v, causal, scale, kb),
        q, k, v, kbias,
    )
    return vjp(do)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_fwd(x2d, gamma, beta, eps, block_rows=256):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2d.shape
    block_rows = _row_block(R, block_rows)
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((H,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, H), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, H), x2d.dtype),
        interpret=_interpret(),
    )(x2d, gamma, beta)


def _ln_dense(x2d, gamma, beta, eps):
    x = x2d.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x2d, gamma, beta, eps=1e-5):
    """Row-fused layer norm over [rows, hidden]."""
    return _ln_fwd(x2d, gamma, beta, eps)


def _ln_vjp_fwd(x2d, gamma, beta, eps):
    return _ln_fwd(x2d, gamma, beta, eps), (x2d, gamma, beta)


def _ln_vjp_bwd(eps, res, dy):
    x2d, gamma, beta = res
    _, vjp = jax.vjp(lambda x, g, b: _ln_dense(x, g, b, eps), x2d, gamma, beta)
    return vjp(dy)


fused_layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def use_pallas():
    """Kernel-override dispatch switch (OpKernelType.library analog)."""
    from ..flags import get_flag

    return get_flag("use_pallas")


# ---------------------------------------------------------------------------
# fused GRU sequence kernel (math/jit_kernel.h gru kernels + fused/fusion_gru
# analog): the hidden state lives in VMEM across ALL timesteps, so the
# recurrence reads/writes HBM once per sequence instead of once per step
# ---------------------------------------------------------------------------
def _gru_seq_kernel(x_ref, w_ref, h0_ref, len_ref, o_ref, *, hid, seq_len):
    w = w_ref[:].astype(jnp.float32)  # [H, 3H]
    w_uz = w[:, : 2 * hid]
    w_c = w[:, 2 * hid:]
    lens = len_ref[:].astype(jnp.int32)  # [Bblk]

    def step(t, h):
        xt = x_ref[:, t, :].astype(jnp.float32)  # [Bblk, 3H]
        gates = xt[:, : 2 * hid] + jax.lax.dot(
            h, w_uz, preferred_element_type=jnp.float32
        )
        u = jax.nn.sigmoid(gates[:, :hid])
        r = jax.nn.sigmoid(gates[:, hid:])
        c = jnp.tanh(
            xt[:, 2 * hid:]
            + jax.lax.dot(r * h, w_c, preferred_element_type=jnp.float32)
        )
        h_new = u * c + (1.0 - u) * h
        active = (t < lens)[:, None].astype(jnp.float32)
        h_new = active * h_new + (1.0 - active) * h
        o_ref[:, t, :] = h_new.astype(o_ref.dtype)
        return h_new

    jax.lax.fori_loop(0, seq_len, step, h0_ref[:].astype(jnp.float32))


def _gru_seq_fwd(xproj, w, h0, lens, block_b=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H3 = xproj.shape
    hid = H3 // 3
    block_b = _row_block(B, block_b)
    grid = (_cdiv(B, block_b),)
    return pl.pallas_call(
        functools.partial(_gru_seq_kernel, hid=hid, seq_len=T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, T, H3), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, H3), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, hid), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_b, T, hid), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, T, hid), xproj.dtype),
        interpret=_interpret(),
    )(xproj, w, h0, lens)


def _gru_seq_dense(xproj, w, h0, lens):
    """Reference scan (also the recompute path for the backward pass)."""
    hid = xproj.shape[-1] // 3
    w_uz, w_c = w[:, : 2 * hid], w[:, 2 * hid:]

    def step(h, inp):
        xt, t = inp
        gates = xt[:, : 2 * hid] + h @ w_uz
        u = jax.nn.sigmoid(gates[:, :hid])
        r = jax.nn.sigmoid(gates[:, hid:])
        c = jnp.tanh(xt[:, 2 * hid:] + (r * h) @ w_c)
        h_new = u * c + (1.0 - u) * h
        act = (t < lens)[:, None].astype(h.dtype)
        h_new = act * h_new + (1 - act) * h
        return h_new, h_new

    xs = jnp.swapaxes(xproj, 0, 1)
    ts = jnp.arange(xproj.shape[1])
    _, hs = jax.lax.scan(step, h0, (xs, ts))
    return jnp.swapaxes(hs, 0, 1)


@jax.custom_vjp
def fused_gru(xproj, w, h0, lens):
    """VMEM-resident GRU over padded [B, T, 3H] projected inputs."""
    return _gru_seq_fwd(xproj, w, h0, lens)


def _gru_vjp_fwd(xproj, w, h0, lens):
    return _gru_seq_fwd(xproj, w, h0, lens), (xproj, w, h0, lens)


def _gru_vjp_bwd(res, dy):
    xproj, w, h0, lens = res
    _, vjp = jax.vjp(lambda x, w_, h_: _gru_seq_dense(x, w_, h_, lens),
                     xproj, w, h0)
    dx, dw, dh0 = vjp(dy)
    return dx, dw, dh0, None


fused_gru.defvjp(_gru_vjp_fwd, _gru_vjp_bwd)


# ---------------------------------------------------------------------------
# fused softmax cross entropy (row-blocked logsumexp + label gather; the
# backward is the analytic softmax(x) - onehot, no recompute needed)
# ---------------------------------------------------------------------------
def _sxent_kernel(x_ref, lbl_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)  # [Bblk, C]
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    lbl = lbl_ref[:].astype(jnp.int32)  # [Bblk]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    gold = jnp.sum(jnp.where(cols == lbl[:, None], x, 0.0), axis=-1,
                   keepdims=True)
    o_ref[:] = (lse - gold).astype(o_ref.dtype)


def _sxent_fwd_call(logits, labels, block_rows=512):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = logits.shape
    block_rows = _row_block(R, block_rows)
    grid = (_cdiv(R, block_rows),)
    return pl.pallas_call(
        _sxent_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.float32),
        interpret=_interpret(),
    )(logits, labels)


@jax.custom_vjp
def fused_softmax_xent(logits, labels):
    """Per-row -log softmax[label] over [rows, classes] + int labels [rows]."""
    return _sxent_fwd_call(logits, labels)


def _sxent_vjp_fwd(logits, labels):
    return _sxent_fwd_call(logits, labels), (logits, labels)


def _sxent_vjp_bwd(res, dy):
    logits, labels = res
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return ((p - onehot) * dy.astype(jnp.float32)).astype(logits.dtype), None


fused_softmax_xent.defvjp(_sxent_vjp_fwd, _sxent_vjp_bwd)
