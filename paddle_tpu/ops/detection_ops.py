"""Detection op lowerings (operators/detection/): box_coder, anchor
generators, bipartite matching, target assignment, RoI pooling, NMS.

Padded design: the reference emits LoD-shaped variable-count outputs (e.g.
NMS keeps a different number of boxes per image); on TPU every op returns
fixed-shape padded results plus counts/masks, so the whole detection head
stays inside one XLA program.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register


@register("box_coder", no_grad_inputs=("PriorBox", "PriorBoxVar"))
def _box_coder(ctx, ins, attrs):
    """encode_center_size / decode_center_size (detection/box_coder_op.cc).
    PriorBox [M, 4] (xmin,ymin,xmax,ymax), TargetBox encode: [N, 4],
    decode: [N, M, 4] offsets."""
    prior = ins["PriorBox"][0]
    target = ins["TargetBox"][0]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    code_type = attrs.get("code_type", "encode_center_size")
    normalized = attrs.get("box_normalized", True)
    off = 0.0 if normalized else 1.0

    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5

    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        # broadcast: out[n, m]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(tw[:, None] / pw[None, :])
        oh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([ox, oy, ow, oh], axis=2)
        if pvar is not None:
            out = out / pvar[None, :, :]
    else:
        t = target  # [N, M, 4]
        if pvar is not None:
            t = t * pvar[None, :, :]
        dcx = t[..., 0] * pw[None, :] + pcx[None, :]
        dcy = t[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(t[..., 2]) * pw[None, :]
        dh = jnp.exp(t[..., 3]) * ph[None, :]
        out = jnp.stack(
            [dcx - dw * 0.5, dcy - dh * 0.5, dcx + dw * 0.5 - off, dcy + dh * 0.5 - off],
            axis=-1,
        )
    return {"OutputBox": [out]}


@register("anchor_generator", no_grad_inputs=("Input",))
def _anchor_generator(ctx, ins, attrs):
    x = ins["Input"][0]  # feature map [N, C, H, W]
    sizes = attrs["anchor_sizes"]
    ratios = attrs["aspect_ratios"]
    stride = attrs["stride"]  # [sw, sh]
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = x.shape[2], x.shape[3]
    num_anchors = len(sizes) * len(ratios)
    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * np.sqrt(r)
            ah = s / np.sqrt(r)
            anchors.append((aw, ah))
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    gx, gy = jnp.meshgrid(cx, cy)  # [H, W]
    out = []
    for aw, ah in anchors:
        out.append(
            jnp.stack(
                [gx - aw / 2, gy - ah / 2, gx + aw / 2, gy + ah / 2], axis=-1
            )
        )
    boxes = jnp.stack(out, axis=2)  # [H, W, A, 4]
    var = jnp.broadcast_to(
        jnp.asarray(variances, boxes.dtype), (h, w, num_anchors, 4)
    )
    return {"Anchors": [boxes], "Variances": [var]}


@register("density_prior_box", no_grad_inputs=("Input", "Image"))
def _density_prior_box(ctx, ins, attrs):
    x = ins["Input"][0]
    img = ins["Image"][0]
    h, w = x.shape[2], x.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [])
    densities = attrs.get("densities", [])
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    step_w = attrs.get("step_w", 0.0) or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    boxes_per_cell = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    sx = -size / 2.0 + step / 2.0 + dj * step
                    sy = -size / 2.0 + step / 2.0 + di * step
                    boxes_per_cell.append((sx, sy, bw, bh))
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    gx, gy = jnp.meshgrid(cx, cy)
    outs = []
    for sx, sy, bw, bh in boxes_per_cell:
        bx = gx + sx
        by = gy + sy
        outs.append(
            jnp.stack(
                [
                    (bx - bw / 2) / iw,
                    (by - bh / 2) / ih,
                    (bx + bw / 2) / iw,
                    (by + bh / 2) / ih,
                ],
                axis=-1,
            )
        )
    boxes = jnp.clip(jnp.stack(outs, axis=2), 0.0, 1.0)  # [H, W, A, 4]
    a = boxes.shape[2]
    var = jnp.broadcast_to(jnp.asarray(variances, boxes.dtype), (h, w, a, 4))
    return {"Boxes": [boxes], "Variances": [var]}


def _iou_matrix(a, b, off=0.0):
    # a [N,4], b [M,4] -> [N,M]
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * jnp.maximum(
        a[:, 3] - a[:, 1] + off, 0
    )
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * jnp.maximum(
        b[:, 3] - b[:, 1] + off, 0
    )
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + off, 0)
    ih = jnp.maximum(iy2 - iy1 + off, 0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("bipartite_match", no_grad_inputs=("DistMat",))
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (detection/bipartite_match_op.cc): N
    rounds of global-argmax + row/col elimination, then (per_prediction)
    fill unmatched cols above the overlap threshold."""
    dist = ins["DistMat"][0]  # [N rows (gt), M cols (prior)]
    match_type = attrs.get("match_type", "bipartite")
    thresh = attrs.get("dist_threshold", 0.5)
    n, m = dist.shape

    def body(i, state):
        d, row_of_col, dist_of_col = state
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        v = d[r, c]
        ok = v > -1e9
        row_of_col = jnp.where(
            ok, row_of_col.at[c].set(r.astype(jnp.int32)), row_of_col
        )
        dist_of_col = jnp.where(ok, dist_of_col.at[c].set(v), dist_of_col)
        d = jnp.where(ok, d.at[r, :].set(-1e10).at[:, c].set(-1e10), d)
        return d, row_of_col, dist_of_col

    row_of_col = jnp.full((m,), -1, jnp.int32)
    dist_of_col = jnp.zeros((m,), dist.dtype)
    _, row_of_col, dist_of_col = jax.lax.fori_loop(
        0, min(n, m), body, (dist, row_of_col, dist_of_col)
    )
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        fill = (row_of_col < 0) & (best_val >= thresh)
        row_of_col = jnp.where(fill, best_row, row_of_col)
        dist_of_col = jnp.where(fill, best_val, dist_of_col)
    return {
        "ColToRowMatchIndices": [row_of_col.reshape(1, -1)],
        "ColToRowMatchDist": [dist_of_col.reshape(1, -1)],
    }


@register("target_assign", no_grad_inputs=("X", "MatchIndices", "NegIndices"))
def _target_assign(ctx, ins, attrs):
    """Gather per-prior targets by match indices (target_assign_op.cc):
    out[i, j] = x[match[i, j]] (per batch row i), weight 1 where matched."""
    x = ins["X"][0]  # [P, K] entity table (gt boxes or labels), padded
    match = ins["MatchIndices"][0].astype(jnp.int32)  # [N, M]
    mismatch_value = attrs.get("mismatch_value", 0)
    nbatch, m = match.shape
    k = x.shape[-1]
    safe = jnp.maximum(match, 0)
    gathered = x[safe.reshape(-1)].reshape(nbatch, m, k)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, gathered, jnp.asarray(mismatch_value, x.dtype))
    wt = matched.astype(jnp.float32)
    return {"Out": [out], "OutWeight": [wt.astype(jnp.float32)]}


@register("roi_pool", no_grad_inputs=("ROIs",))
def _roi_pool(ctx, ins, attrs):
    """RoI max pooling (detection-era roi_pool_op.cc): rois [R, 4] in image
    coords + RoisBatch [R] image index (padded replacement for LoD)."""
    x = ins["X"][0]  # [N, C, H, W]
    rois = ins["ROIs"][0]  # [R, 4]
    batch_idx = (
        ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
        if ins.get("RoisBatch")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    spatial_scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def pool_one(roi, bi):
        x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[bi]  # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def cell(i, j):
            ys0 = y1 + (i * rh) // ph
            ys1 = y1 + ((i + 1) * rh + ph - 1) // ph
            xs0 = x1 + (j * rw) // pw
            xs1 = x1 + ((j + 1) * rw + pw - 1) // pw
            mask = (
                (ys[None, :, None] >= ys0)
                & (ys[None, :, None] < jnp.maximum(ys1, ys0 + 1))
                & (xs[None, None, :] >= xs0)
                & (xs[None, None, :] < jnp.maximum(xs1, xs0 + 1))
            )
            return jnp.max(jnp.where(mask, img, -jnp.inf), axis=(1, 2))

        cells = jnp.stack(
            [jnp.stack([cell(i, j) for j in range(pw)], -1) for i in range(ph)], -2
        )  # [C, ph, pw]
        return jnp.where(jnp.isfinite(cells), cells, 0.0)

    out = jax.vmap(pool_one)(rois, batch_idx)  # [R, C, ph, pw]
    return {"Out": [out], "Argmax": [jnp.zeros(out.shape, jnp.int32)]}


@register("roi_align", no_grad_inputs=("ROIs",))
def _roi_align(ctx, ins, attrs):
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    batch_idx = (
        ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
        if ins.get("RoisBatch")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    spatial_scale = attrs.get("spatial_scale", 1.0)
    sampling = attrs.get("sampling_ratio", -1)
    s = 2 if sampling <= 0 else sampling
    n, c, h, w = x.shape

    def bilinear(img, y, x_):
        y0 = jnp.floor(y)
        x0 = jnp.floor(x_)
        wy = y - y0
        wx = x_ - x0

        def g(yy, xx):
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            return img[:, yc, xc]

        return (
            g(y0, x0) * (1 - wy) * (1 - wx)
            + g(y0, x0 + 1) * (1 - wy) * wx
            + g(y0 + 1, x0) * wy * (1 - wx)
            + g(y0 + 1, x0 + 1) * wy * wx
        )

    def pool_one(roi, bi):
        x1, y1, x2, y2 = (
            roi[0] * spatial_scale,
            roi[1] * spatial_scale,
            roi[2] * spatial_scale,
            roi[3] * spatial_scale,
        )
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[bi]
        vals = []
        for i in range(ph):
            row = []
            for j in range(pw):
                acc = 0.0
                for si in range(s):
                    for sj in range(s):
                        yy = y1 + bin_h * (i + (si + 0.5) / s)
                        xx = x1 + bin_w * (j + (sj + 0.5) / s)
                        acc = acc + bilinear(img, yy, xx)
                row.append(acc / (s * s))
            vals.append(jnp.stack(row, -1))
        return jnp.stack(vals, -2)  # [C, ph, pw]

    out = jax.vmap(pool_one)(rois, batch_idx)
    return {"Out": [out]}


@register("multiclass_nms", no_grad_inputs=("BBoxes", "Scores"))
def _multiclass_nms(ctx, ins, attrs):
    """Per-class NMS + cross-class top-k (detection/multiclass_nms_op.cc).
    Padded contract: BBoxes [N, M, 4], Scores [N, C, M]; output
    Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded with
    label=-1, plus NmsRoisNum [N]."""
    bboxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    score_thresh = attrs.get("score_threshold", 0.01)
    nms_thresh = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 200)
    bg_label = attrs.get("background_label", 0)
    nb, nc, m = scores.shape
    nms_top_k = min(nms_top_k if nms_top_k > 0 else m, m)

    def nms_class(box, sc):
        # box [M, 4], sc [M] -> suppressed score vector [nms_top_k] + index
        top_sc, top_idx = jax.lax.top_k(sc, nms_top_k)
        top_box = box[top_idx]
        iou = _iou_matrix(top_box, top_box)

        def body(i, keep):
            # suppress j>i overlapping too much with any kept i
            cur_keep = keep[i] & (top_sc[i] > score_thresh)
            over = (iou[i] > nms_thresh) & (jnp.arange(nms_top_k) > i)
            keep = jnp.where(cur_keep, keep & ~over, keep)
            return keep

        keep = jnp.ones((nms_top_k,), jnp.bool_)
        keep = jax.lax.fori_loop(0, nms_top_k, body, keep)
        keep = keep & (top_sc > score_thresh)
        return jnp.where(keep, top_sc, -1.0), top_idx

    # single-class heads have no background column to skip
    fg_classes = [c for c in range(nc) if c != bg_label] or list(range(nc))

    def per_image(box, sc):
        all_sc = []
        all_idx = []
        all_lab = []
        for c in fg_classes:
            s_c, i_c = nms_class(box, sc[c])
            all_sc.append(s_c)
            all_idx.append(i_c)
            all_lab.append(jnp.full((nms_top_k,), c, jnp.int32))
        cat_sc = jnp.concatenate(all_sc)
        cat_idx = jnp.concatenate(all_idx)
        cat_lab = jnp.concatenate(all_lab)
        k = min(keep_top_k if keep_top_k > 0 else cat_sc.shape[0], cat_sc.shape[0])
        fin_sc, fin_pos = jax.lax.top_k(cat_sc, k)
        fin_idx = cat_idx[fin_pos]
        fin_lab = jnp.where(fin_sc > 0, cat_lab[fin_pos], -1)
        fin_box = box[fin_idx]
        out = jnp.concatenate(
            [fin_lab[:, None].astype(box.dtype), fin_sc[:, None], fin_box], axis=1
        )
        return out, jnp.sum((fin_sc > 0).astype(jnp.int32))

    outs, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}


@register("polygon_box_transform", no_grad_inputs=("Input",))
def _polygon_box_transform(ctx, ins, attrs):
    x = ins["Input"][0]  # [N, G*2, H, W] offsets
    n, g2, h, w = x.shape
    gx = jnp.arange(w, dtype=x.dtype).reshape(1, 1, 1, w)
    gy = jnp.arange(h, dtype=x.dtype).reshape(1, 1, h, 1)
    idx = jnp.arange(g2) % 2
    grid = jnp.where(idx.reshape(1, -1, 1, 1) == 0, gx * 4, gy * 4)
    return {"Output": [jnp.where(x != 0, grid - x, x)]}


@register("generate_proposal_labels_placeholder", no_grad_inputs=None)
def _gpl(ctx, ins, attrs):
    raise NotImplementedError(
        "generate_proposal_labels: use the python-side sampler in "
        "layers/detection.py (host pre-processing, not a TPU kernel)"
    )
