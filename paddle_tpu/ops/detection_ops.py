"""Detection op lowerings (operators/detection/): box_coder, anchor
generators, bipartite matching, target assignment, RoI pooling, NMS.

Padded design: the reference emits LoD-shaped variable-count outputs (e.g.
NMS keeps a different number of boxes per image); on TPU every op returns
fixed-shape padded results plus counts/masks, so the whole detection head
stays inside one XLA program.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register


@register("box_coder", no_grad_inputs=("PriorBox", "PriorBoxVar"))
def _box_coder(ctx, ins, attrs):
    """encode_center_size / decode_center_size (detection/box_coder_op.cc).
    PriorBox [M, 4] (xmin,ymin,xmax,ymax), TargetBox encode: [N, 4],
    decode: [N, M, 4] offsets."""
    prior = ins["PriorBox"][0]
    target = ins["TargetBox"][0]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    code_type = attrs.get("code_type", "encode_center_size")
    normalized = attrs.get("box_normalized", True)
    off = 0.0 if normalized else 1.0

    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5

    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        # broadcast: out[n, m]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(tw[:, None] / pw[None, :])
        oh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([ox, oy, ow, oh], axis=2)
        if pvar is not None:
            out = out / pvar[None, :, :]
    else:
        t = target  # [N, M, 4]
        if pvar is not None:
            t = t * pvar[None, :, :]
        dcx = t[..., 0] * pw[None, :] + pcx[None, :]
        dcy = t[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(t[..., 2]) * pw[None, :]
        dh = jnp.exp(t[..., 3]) * ph[None, :]
        out = jnp.stack(
            [dcx - dw * 0.5, dcy - dh * 0.5, dcx + dw * 0.5 - off, dcy + dh * 0.5 - off],
            axis=-1,
        )
    return {"OutputBox": [out]}


@register("anchor_generator", no_grad_inputs=("Input",))
def _anchor_generator(ctx, ins, attrs):
    x = ins["Input"][0]  # feature map [N, C, H, W]
    sizes = attrs["anchor_sizes"]
    ratios = attrs["aspect_ratios"]
    stride = attrs["stride"]  # [sw, sh]
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = x.shape[2], x.shape[3]
    num_anchors = len(sizes) * len(ratios)
    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * np.sqrt(r)
            ah = s / np.sqrt(r)
            anchors.append((aw, ah))
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    gx, gy = jnp.meshgrid(cx, cy)  # [H, W]
    out = []
    for aw, ah in anchors:
        out.append(
            jnp.stack(
                [gx - aw / 2, gy - ah / 2, gx + aw / 2, gy + ah / 2], axis=-1
            )
        )
    boxes = jnp.stack(out, axis=2)  # [H, W, A, 4]
    var = jnp.broadcast_to(
        jnp.asarray(variances, boxes.dtype), (h, w, num_anchors, 4)
    )
    return {"Anchors": [boxes], "Variances": [var]}


@register("density_prior_box", no_grad_inputs=("Input", "Image"))
def _density_prior_box(ctx, ins, attrs):
    x = ins["Input"][0]
    img = ins["Image"][0]
    h, w = x.shape[2], x.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [])
    densities = attrs.get("densities", [])
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    step_w = attrs.get("step_w", 0.0) or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    boxes_per_cell = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    sx = -size / 2.0 + step / 2.0 + dj * step
                    sy = -size / 2.0 + step / 2.0 + di * step
                    boxes_per_cell.append((sx, sy, bw, bh))
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    gx, gy = jnp.meshgrid(cx, cy)
    outs = []
    for sx, sy, bw, bh in boxes_per_cell:
        bx = gx + sx
        by = gy + sy
        outs.append(
            jnp.stack(
                [
                    (bx - bw / 2) / iw,
                    (by - bh / 2) / ih,
                    (bx + bw / 2) / iw,
                    (by + bh / 2) / ih,
                ],
                axis=-1,
            )
        )
    boxes = jnp.clip(jnp.stack(outs, axis=2), 0.0, 1.0)  # [H, W, A, 4]
    a = boxes.shape[2]
    var = jnp.broadcast_to(jnp.asarray(variances, boxes.dtype), (h, w, a, 4))
    return {"Boxes": [boxes], "Variances": [var]}


def _iou_matrix(a, b, off=0.0):
    # a [N,4], b [M,4] -> [N,M]
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * jnp.maximum(
        a[:, 3] - a[:, 1] + off, 0
    )
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * jnp.maximum(
        b[:, 3] - b[:, 1] + off, 0
    )
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + off, 0)
    ih = jnp.maximum(iy2 - iy1 + off, 0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("bipartite_match", no_grad_inputs=("DistMat",))
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (detection/bipartite_match_op.cc): N
    rounds of global-argmax + row/col elimination, then (per_prediction)
    fill unmatched cols above the overlap threshold."""
    dist = ins["DistMat"][0]  # [N rows (gt), M cols (prior)]
    match_type = attrs.get("match_type", "bipartite")
    thresh = attrs.get("dist_threshold", 0.5)
    n, m = dist.shape

    def body(i, state):
        d, row_of_col, dist_of_col = state
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        v = d[r, c]
        ok = v > -1e9
        row_of_col = jnp.where(
            ok, row_of_col.at[c].set(r.astype(jnp.int32)), row_of_col
        )
        dist_of_col = jnp.where(ok, dist_of_col.at[c].set(v), dist_of_col)
        d = jnp.where(ok, d.at[r, :].set(-1e10).at[:, c].set(-1e10), d)
        return d, row_of_col, dist_of_col

    row_of_col = jnp.full((m,), -1, jnp.int32)
    dist_of_col = jnp.zeros((m,), dist.dtype)
    _, row_of_col, dist_of_col = jax.lax.fori_loop(
        0, min(n, m), body, (dist, row_of_col, dist_of_col)
    )
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        fill = (row_of_col < 0) & (best_val >= thresh)
        row_of_col = jnp.where(fill, best_row, row_of_col)
        dist_of_col = jnp.where(fill, best_val, dist_of_col)
    return {
        "ColToRowMatchIndices": [row_of_col.reshape(1, -1)],
        "ColToRowMatchDist": [dist_of_col.reshape(1, -1)],
    }


@register("target_assign", no_grad_inputs=("X", "MatchIndices", "NegIndices"))
def _target_assign(ctx, ins, attrs):
    """Gather per-prior targets by match indices (target_assign_op.cc):
    out[i, j] = x[match[i, j]] (per batch row i), weight 1 where matched."""
    x = ins["X"][0]  # [P, K] entity table (gt boxes or labels), padded
    match = ins["MatchIndices"][0].astype(jnp.int32)  # [N, M]
    mismatch_value = attrs.get("mismatch_value", 0)
    nbatch, m = match.shape
    k = x.shape[-1]
    safe = jnp.maximum(match, 0)
    gathered = x[safe.reshape(-1)].reshape(nbatch, m, k)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, gathered, jnp.asarray(mismatch_value, x.dtype))
    wt = matched.astype(jnp.float32)
    return {"Out": [out], "OutWeight": [wt.astype(jnp.float32)]}


@register("roi_pool", no_grad_inputs=("ROIs",))
def _roi_pool(ctx, ins, attrs):
    """RoI max pooling (detection-era roi_pool_op.cc): rois [R, 4] in image
    coords + RoisBatch [R] image index (padded replacement for LoD)."""
    x = ins["X"][0]  # [N, C, H, W]
    rois = ins["ROIs"][0]  # [R, 4]
    batch_idx = (
        ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
        if ins.get("RoisBatch")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    spatial_scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def pool_one(roi, bi):
        x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[bi]  # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def cell(i, j):
            ys0 = y1 + (i * rh) // ph
            ys1 = y1 + ((i + 1) * rh + ph - 1) // ph
            xs0 = x1 + (j * rw) // pw
            xs1 = x1 + ((j + 1) * rw + pw - 1) // pw
            mask = (
                (ys[None, :, None] >= ys0)
                & (ys[None, :, None] < jnp.maximum(ys1, ys0 + 1))
                & (xs[None, None, :] >= xs0)
                & (xs[None, None, :] < jnp.maximum(xs1, xs0 + 1))
            )
            return jnp.max(jnp.where(mask, img, -jnp.inf), axis=(1, 2))

        cells = jnp.stack(
            [jnp.stack([cell(i, j) for j in range(pw)], -1) for i in range(ph)], -2
        )  # [C, ph, pw]
        return jnp.where(jnp.isfinite(cells), cells, 0.0)

    out = jax.vmap(pool_one)(rois, batch_idx)  # [R, C, ph, pw]
    return {"Out": [out], "Argmax": [jnp.zeros(out.shape, jnp.int32)]}


@register("roi_align", no_grad_inputs=("ROIs",))
def _roi_align(ctx, ins, attrs):
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    batch_idx = (
        ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
        if ins.get("RoisBatch")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    spatial_scale = attrs.get("spatial_scale", 1.0)
    sampling = attrs.get("sampling_ratio", -1)
    s = 2 if sampling <= 0 else sampling
    n, c, h, w = x.shape

    def bilinear(img, y, x_):
        y0 = jnp.floor(y)
        x0 = jnp.floor(x_)
        wy = y - y0
        wx = x_ - x0

        def g(yy, xx):
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            return img[:, yc, xc]

        return (
            g(y0, x0) * (1 - wy) * (1 - wx)
            + g(y0, x0 + 1) * (1 - wy) * wx
            + g(y0 + 1, x0) * wy * (1 - wx)
            + g(y0 + 1, x0 + 1) * wy * wx
        )

    def pool_one(roi, bi):
        x1, y1, x2, y2 = (
            roi[0] * spatial_scale,
            roi[1] * spatial_scale,
            roi[2] * spatial_scale,
            roi[3] * spatial_scale,
        )
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[bi]
        vals = []
        for i in range(ph):
            row = []
            for j in range(pw):
                acc = 0.0
                for si in range(s):
                    for sj in range(s):
                        yy = y1 + bin_h * (i + (si + 0.5) / s)
                        xx = x1 + bin_w * (j + (sj + 0.5) / s)
                        acc = acc + bilinear(img, yy, xx)
                row.append(acc / (s * s))
            vals.append(jnp.stack(row, -1))
        return jnp.stack(vals, -2)  # [C, ph, pw]

    out = jax.vmap(pool_one)(rois, batch_idx)
    return {"Out": [out]}


@register("multiclass_nms", no_grad_inputs=("BBoxes", "Scores"))
def _multiclass_nms(ctx, ins, attrs):
    """Per-class NMS + cross-class top-k (detection/multiclass_nms_op.cc).
    Padded contract: BBoxes [N, M, 4], Scores [N, C, M]; output
    Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded with
    label=-1, plus NmsRoisNum [N]."""
    bboxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    score_thresh = attrs.get("score_threshold", 0.01)
    nms_thresh = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 200)
    bg_label = attrs.get("background_label", 0)
    nb, nc, m = scores.shape
    nms_top_k = min(nms_top_k if nms_top_k > 0 else m, m)

    def nms_class(box, sc):
        # box [M, 4], sc [M] -> suppressed score vector [nms_top_k] + index
        top_sc, top_idx = jax.lax.top_k(sc, nms_top_k)
        top_box = box[top_idx]
        keep = _nms_keep(top_box, top_sc, nms_thresh, score_thresh)
        return jnp.where(keep, top_sc, -1.0), top_idx

    # single-class heads have no background column to skip
    fg_classes = [c for c in range(nc) if c != bg_label] or list(range(nc))

    def per_image(box, sc):
        all_sc = []
        all_idx = []
        all_lab = []
        for c in fg_classes:
            s_c, i_c = nms_class(box, sc[c])
            all_sc.append(s_c)
            all_idx.append(i_c)
            all_lab.append(jnp.full((nms_top_k,), c, jnp.int32))
        cat_sc = jnp.concatenate(all_sc)
        cat_idx = jnp.concatenate(all_idx)
        cat_lab = jnp.concatenate(all_lab)
        k = min(keep_top_k if keep_top_k > 0 else cat_sc.shape[0], cat_sc.shape[0])
        fin_sc, fin_pos = jax.lax.top_k(cat_sc, k)
        fin_idx = cat_idx[fin_pos]
        fin_lab = jnp.where(fin_sc > 0, cat_lab[fin_pos], -1)
        fin_box = box[fin_idx]
        out = jnp.concatenate(
            [fin_lab[:, None].astype(box.dtype), fin_sc[:, None], fin_box], axis=1
        )
        return out, jnp.sum((fin_sc > 0).astype(jnp.int32))

    outs, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}


@register("polygon_box_transform", no_grad_inputs=("Input",))
def _polygon_box_transform(ctx, ins, attrs):
    x = ins["Input"][0]  # [N, G*2, H, W] offsets
    n, g2, h, w = x.shape
    gx = jnp.arange(w, dtype=x.dtype).reshape(1, 1, 1, w)
    gy = jnp.arange(h, dtype=x.dtype).reshape(1, 1, h, 1)
    idx = jnp.arange(g2) % 2
    grid = jnp.where(idx.reshape(1, -1, 1, 1) == 0, gx * 4, gy * 4)
    return {"Output": [jnp.where(x != 0, grid - x, x)]}


def _encode_center_size(gt, prior, pvar, off=0.0):
    """Row-wise box encoding: gt [K, 4] against prior [K, 4] (aligned)."""
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    tw = jnp.maximum(gt[:, 2] - gt[:, 0] + off, 1e-6)
    th = jnp.maximum(gt[:, 3] - gt[:, 1] + off, 1e-6)
    tcx = gt[:, 0] + tw * 0.5
    tcy = gt[:, 1] + th * 0.5
    out = jnp.stack(
        [
            (tcx - pcx) / pw,
            (tcy - pcy) / ph,
            jnp.log(tw / pw),
            jnp.log(th / ph),
        ],
        axis=1,
    )
    if pvar is not None:
        out = out / pvar
    return out


def _decode_center_size(deltas, prior, pvar, off=0.0):
    """Row-wise decode: deltas [K, 4] applied to prior [K, 4]."""
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    d = deltas * pvar if pvar is not None else deltas
    dcx = d[:, 0] * pw + pcx
    dcy = d[:, 1] * ph + pcy
    dw = jnp.exp(jnp.clip(d[:, 2], -10.0, 10.0)) * pw
    dh = jnp.exp(jnp.clip(d[:, 3], -10.0, 10.0)) * ph
    return jnp.stack(
        [dcx - dw * 0.5, dcy - dh * 0.5, dcx + dw * 0.5 - off, dcy + dh * 0.5 - off],
        axis=1,
    )


def _nms_keep(boxes, scores, nms_thresh, score_thresh=-jnp.inf):
    """Dense greedy NMS on score-sorted boxes: returns keep mask [K]."""
    k = boxes.shape[0]
    iou = _iou_matrix(boxes, boxes)

    def body(i, keep):
        cur = keep[i] & (scores[i] > score_thresh)
        over = (iou[i] > nms_thresh) & (jnp.arange(k) > i)
        return jnp.where(cur, keep & ~over, keep)

    keep = jax.lax.fori_loop(0, k, body, jnp.ones((k,), jnp.bool_))
    return keep & (scores > score_thresh)


@register("generate_proposals", no_grad_inputs=("Scores", "BboxDeltas", "ImInfo", "Anchors", "Variances"))
def _generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (detection/generate_proposals_op.cc).

    Padded contract: Scores [N, A, H, W], BboxDeltas [N, 4A, H, W],
    Anchors [H, W, A, 4], ImInfo [N, 3] (h, w, scale).  Output
    RpnRois [N, post_nms_topN, 4] + RpnRoiProbs + RpnRoisNum — fixed shapes
    (the reference emits LoD var-count rois), invalid rows zeroed.
    """
    scores = ins["Scores"][0]
    deltas = ins["BboxDeltas"][0]
    im_info = ins["ImInfo"][0]
    anchors = ins["Anchors"][0].reshape(-1, 4)
    variances = (
        ins["Variances"][0].reshape(-1, 4) if ins.get("Variances") else None
    )
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    n, a, h, w = scores.shape
    total = a * h * w
    pre_n = min(pre_n, total)
    post_n = min(post_n, pre_n)

    def per_image(sc, dl, info):
        sc = jnp.transpose(sc, (1, 2, 0)).reshape(-1)  # [H*W*A]
        dl = jnp.transpose(dl.reshape(a, 4, h, w), (2, 3, 0, 1)).reshape(-1, 4)
        # anchors [H, W, A, 4] were flattened to the same H*W*A row order
        boxes = _decode_center_size(dl, anchors, variances)
        ih, iw = info[0], info[1]
        boxes = jnp.stack(
            [
                jnp.clip(boxes[:, 0], 0, iw - 1),
                jnp.clip(boxes[:, 1], 0, ih - 1),
                jnp.clip(boxes[:, 2], 0, iw - 1),
                jnp.clip(boxes[:, 3], 0, ih - 1),
            ],
            axis=1,
        )
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        ms = min_size * info[2]
        valid = (ws >= ms) & (hs >= ms)
        sc = jnp.where(valid, sc, -jnp.inf)
        top_sc, top_idx = jax.lax.top_k(sc, pre_n)
        top_box = boxes[top_idx]
        keep = _nms_keep(top_box, top_sc, nms_thresh)
        kept_sc = jnp.where(keep, top_sc, -jnp.inf)
        fin_sc, fin_pos = jax.lax.top_k(kept_sc, post_n)
        fin_box = top_box[fin_pos]
        ok = jnp.isfinite(fin_sc)
        fin_box = jnp.where(ok[:, None], fin_box, 0.0)
        fin_sc = jnp.where(ok, fin_sc, 0.0)
        return fin_box, fin_sc[:, None], jnp.sum(ok.astype(jnp.int32))

    rois, probs, counts = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs], "RpnRoisNum": [counts]}


@register(
    "rpn_target_assign",
    no_grad_inputs=("Anchor", "GtBoxes", "IsCrowd", "ImInfo", "GtNum"),
    needs_rng=True,
)
def _rpn_target_assign(ctx, ins, attrs):
    """RPN anchor labeling + sampling (detection/rpn_target_assign_op.cc).

    Dense re-expression: instead of the reference's gathered index lists
    (dynamic length), emits per-anchor labels [N, A] (1 fg / 0 bg / -1
    ignore, subsampled to rpn_batch_size_per_im with fg_fraction),
    regression targets [N, A, 4] and inside weights [N, A, 4] — consumers
    mask by label instead of gathering.
    """
    anchors = ins["Anchor"][0].reshape(-1, 4)  # [A, 4]
    gts = ins["GtBoxes"][0]  # [N, G, 4] padded
    gt_num = (
        ins["GtNum"][0].reshape(-1).astype(jnp.int32)
        if ins.get("GtNum")
        else jnp.full((gts.shape[0],), gts.shape[1], jnp.int32)
    )
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_ov = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_ov = float(attrs.get("rpn_negative_overlap", 0.3))
    a = anchors.shape[0]
    g = gts.shape[1]
    key = ctx.rng(attrs)

    def per_image(gt, cnt, k):
        valid = jnp.arange(g) < cnt
        iou = _iou_matrix(gt, anchors)  # [G, A]
        iou = jnp.where(valid[:, None], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=0)  # per anchor
        best_iou = jnp.max(iou, axis=0)
        label = jnp.full((a,), -1, jnp.int32)
        label = jnp.where(best_iou >= pos_ov, 1, label)
        label = jnp.where((best_iou < neg_ov) & (best_iou >= 0), 0, label)
        # force: the best anchor per valid gt is fg (tie contract of the
        # reference's "anchor with highest overlap for each gt"); padded gt
        # rows scatter out of range so they cannot clobber anchor 0
        best_a_per_g = jnp.argmax(iou, axis=1)  # [G]
        force_idx = jnp.where(valid, best_a_per_g, a)
        force = jnp.zeros((a,), jnp.bool_).at[force_idx].set(True, mode="drop")
        label = jnp.where(force, 1, label)
        # subsample: random keep of at most fg_cap fg / rest bg
        fg_cap = int(batch * fg_frac)
        r = jax.random.uniform(k, (a,))
        fg = label == 1
        fg_rank = jnp.argsort(jnp.argsort(jnp.where(fg, r, 2.0)))
        label = jnp.where(fg & (fg_rank >= fg_cap), -1, label)
        n_fg = jnp.minimum(jnp.sum(fg), fg_cap)
        bg_cap = batch - n_fg
        bg = label == 0
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(bg, r, 2.0)))
        label = jnp.where(bg & (bg_rank >= bg_cap), -1, label)
        tgt = _encode_center_size(gt[best_gt], anchors, None)
        tgt = jnp.where((label == 1)[:, None], tgt, 0.0)
        inw = jnp.where((label == 1)[:, None], 1.0, 0.0)
        return label, tgt, inw

    keys = jax.random.split(key, gts.shape[0])
    labels, tgts, inws = jax.vmap(per_image)(gts, gt_num, keys)
    return {
        "TargetLabel": [labels],
        "TargetBBox": [tgts],
        "BBoxInsideWeight": [inws.astype(jnp.float32)],
    }


@register(
    "generate_proposal_labels",
    no_grad_inputs=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes", "ImInfo", "RpnRoisNum", "GtNum"),
    needs_rng=True,
)
def _generate_proposal_labels(ctx, ins, attrs):
    """Second-stage RoI sampling (detection/generate_proposal_labels_op.cc).

    Dense padded contract: RpnRois [N, R, 4], GtBoxes [N, G, 4],
    GtClasses [N, G]; outputs Rois [N, B, 4], LabelsInt32 [N, B],
    BboxTargets [N, B, 4C], BboxInsideWeights / BboxOutsideWeights
    [N, B, 4C] with B = batch_size_per_im (fg sampled to fg_fraction,
    padding rows labeled -1).
    """
    rois = ins["RpnRois"][0]
    gts = ins["GtBoxes"][0]
    gtc = ins["GtClasses"][0]
    gt_num = (
        ins["GtNum"][0].reshape(-1).astype(jnp.int32)
        if ins.get("GtNum")
        else jnp.full((gts.shape[0],), gts.shape[1], jnp.int32)
    )
    roi_num = (
        ins["RpnRoisNum"][0].reshape(-1).astype(jnp.int32)
        if ins.get("RpnRoisNum")
        else jnp.full((rois.shape[0],), rois.shape[1], jnp.int32)
    )
    bs = int(attrs.get("batch_size_per_im", 512))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thresh = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    n_cls = int(attrs.get("class_nums", 81))
    reg_w = jnp.asarray(
        attrs.get("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2]), jnp.float32
    )[None, :]
    g = gts.shape[1]
    r_in = rois.shape[1]
    key = ctx.rng(attrs)

    def per_image(roi, rn, gt, gl, cnt, k):
        # append gt boxes to the roi set (reference behavior)
        allr = jnp.concatenate([roi, gt], axis=0)  # [R+G, 4]
        roi_valid = jnp.concatenate(
            [jnp.arange(r_in) < rn, jnp.arange(g) < cnt]
        )
        iou = _iou_matrix(gt, allr)  # [G, R+G]
        iou = jnp.where((jnp.arange(g) < cnt)[:, None], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=0)
        best_iou = jnp.max(iou, axis=0)
        best_iou = jnp.where(roi_valid, best_iou, -1.0)
        fg = best_iou >= fg_thresh
        bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
        fg_cap = int(bs * fg_frac)
        r = jax.random.uniform(k, (allr.shape[0],))
        fg_rank = jnp.argsort(jnp.argsort(jnp.where(fg, r, 2.0)))
        fg_sel = fg & (fg_rank < fg_cap)
        n_fg = jnp.sum(fg_sel)
        bg_cap = bs - n_fg
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(bg, r, 2.0)))
        bg_sel = bg & (bg_rank < bg_cap)
        sel = fg_sel | bg_sel
        # stable gather of selected rows into the fixed bs-slot output
        order = jnp.argsort(jnp.argsort(jnp.where(sel, r, 2.0)))
        slot = jnp.where(sel, order, bs + 1)
        out_roi = jnp.zeros((bs, 4), roi.dtype)
        out_lab = jnp.full((bs,), -1, jnp.int32)
        out_tgt = jnp.zeros((bs, 4), roi.dtype)
        src_gt = gt[best_gt]
        # regression targets divided by bbox_reg_weights (reference
        # bbox_util BoxToDelta weights semantics)
        enc = _encode_center_size(src_gt, allr, reg_w)
        labs = jnp.where(
            fg_sel, gl.reshape(-1)[best_gt].astype(jnp.int32), 0
        )
        # unselected rows carry slot bs+1 and fall off via mode="drop"
        out_roi = out_roi.at[slot].set(allr, mode="drop")
        out_lab = out_lab.at[slot].set(labs, mode="drop")
        out_tgt = out_tgt.at[slot].set(
            jnp.where(fg_sel[:, None], enc, 0.0), mode="drop"
        )
        # expand targets to per-class layout [B, 4*n_cls]
        lab_idx = jnp.clip(out_lab, 0, n_cls - 1)
        tgt_full = jnp.zeros((bs, n_cls, 4), roi.dtype)
        tgt_full = tgt_full.at[jnp.arange(bs), lab_idx].set(out_tgt)
        w_full = jnp.zeros((bs, n_cls, 4), jnp.float32)
        w_full = w_full.at[jnp.arange(bs), lab_idx].set(
            jnp.where((out_lab > 0)[:, None], 1.0, 0.0)
        )
        return (
            out_roi,
            out_lab,
            tgt_full.reshape(bs, -1),
            w_full.reshape(bs, -1),
            jnp.sum(sel.astype(jnp.int32)),
        )

    keys = jax.random.split(key, rois.shape[0])
    o_roi, o_lab, o_tgt, o_w, o_cnt = jax.vmap(per_image)(
        rois, roi_num, gts, gtc, gt_num, keys
    )
    return {
        "Rois": [o_roi],
        "LabelsInt32": [o_lab],
        "BboxTargets": [o_tgt],
        "BboxInsideWeights": [o_w],
        "BboxOutsideWeights": [o_w],
        "RoisNum": [o_cnt],
    }


@register("mine_hard_examples", no_grad_inputs=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"))
def _mine_hard_examples(ctx, ins, attrs):
    """Hard-negative mining (detection/mine_hard_examples_op.cc).

    Dense contract: ClsLoss [N, P], MatchIndices [N, P] (-1 = unmatched);
    emits NegMask [N, P] (1 = selected hard negative, at most
    neg_pos_ratio * num_pos per image, highest loss first) and
    UpdatedMatchIndices (unselected negatives forced to -1 — parity with
    the reference's output).
    """
    loss = ins["ClsLoss"][0]
    if ins.get("LocLoss"):
        loss = loss + ins["LocLoss"][0]
    match = ins["MatchIndices"][0].astype(jnp.int32)
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_dist = float(attrs.get("neg_dist_threshold", 0.5))
    mdist = ins["MatchDist"][0] if ins.get("MatchDist") else None
    p = match.shape[1]

    def per_image(l, m, d):
        pos = m >= 0
        neg_cand = ~pos
        if d is not None:
            neg_cand = neg_cand & (d < neg_dist)
        n_neg = jnp.minimum(
            (ratio * jnp.sum(pos)).astype(jnp.int32), jnp.sum(neg_cand)
        )
        nl = jnp.where(neg_cand, l, -jnp.inf)
        rank = jnp.argsort(jnp.argsort(-nl))
        neg_sel = neg_cand & (rank < n_neg)
        return neg_sel.astype(jnp.int32), jnp.where(pos | neg_sel, m, -1)

    if mdist is not None:
        neg, upd = jax.vmap(per_image)(loss, match, mdist)
    else:
        neg, upd = jax.vmap(lambda l, m: per_image(l, m, None))(loss, match)
    return {"NegMask": [neg], "UpdatedMatchIndices": [upd]}


@register("ssd_loss", no_grad_inputs=("GtBox", "GtLabel", "PriorBox", "PriorBoxVar", "GtNum"))
def _ssd_loss(ctx, ins, attrs):
    """Fused SSD multibox loss (layers/detection.py ssd_loss composition:
    iou_similarity -> match -> target_assign -> mine_hard_examples ->
    smooth_l1 + softmax CE).  One dense per-image kernel under vmap — the
    TPU re-expression of the reference's 7-op LoD pipeline; differentiable
    w.r.t. Location/Confidence (mining mask is stop-gradient).

    Inputs: Location [N, P, 4], Confidence [N, P, C], GtBox [N, G, 4],
    GtLabel [N, G, 1], PriorBox [P, 4], PriorBoxVar [P, 4], GtNum [N].
    Output: Loss [N, P] per-prior weighted loss.
    """
    loc = ins["Location"][0]
    conf = ins["Confidence"][0]
    gts = ins["GtBox"][0]
    gtl = ins["GtLabel"][0]
    prior = ins["PriorBox"][0]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    gt_num = (
        ins["GtNum"][0].reshape(-1).astype(jnp.int32)
        if ins.get("GtNum")
        else jnp.full((gts.shape[0],), gts.shape[1], jnp.int32)
    )
    ov_thresh = float(attrs.get("overlap_threshold", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    bg_label = int(attrs.get("background_label", 0))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    normalize = bool(attrs.get("normalize", True))
    g = gts.shape[1]

    def per_image(lc, cf, gt, gl, cnt):
        valid = jnp.arange(g) < cnt
        iou = _iou_matrix(gt, prior)  # [G, P]
        iou = jnp.where(valid[:, None], iou, -1.0)
        best_g = jnp.argmax(iou, axis=0)
        best_v = jnp.max(iou, axis=0)
        match = jnp.where(best_v >= ov_thresh, best_g.astype(jnp.int32), -1)
        # force-match the best prior of every valid gt (bipartite step);
        # padded gt rows scatter out of range instead of writing stale
        # values at prior 0
        best_p = jnp.argmax(iou, axis=1)  # [G]
        p_total = match.shape[0]
        force_idx = jnp.where(valid, best_p, p_total)
        match = match.at[force_idx].set(
            jnp.arange(g, dtype=jnp.int32), mode="drop"
        )
        fg = match >= 0
        num_pos = jnp.sum(fg)
        tgt_lab = jnp.where(fg, gl.reshape(-1)[jnp.maximum(match, 0)].astype(jnp.int32), bg_label)
        logp = jax.nn.log_softmax(cf, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_lab[:, None], axis=-1)[:, 0]
        # hard-negative mining on the CE values (selection is constant)
        ce_const = jax.lax.stop_gradient(ce)
        n_neg = jnp.minimum(
            (neg_ratio * num_pos).astype(jnp.int32), jnp.sum(~fg)
        )
        nl = jnp.where(~fg, ce_const, -jnp.inf)
        rank = jnp.argsort(jnp.argsort(-nl))
        neg_sel = (~fg) & (rank < n_neg)
        conf_weight = fg | neg_sel
        enc = _encode_center_size(gt[jnp.maximum(match, 0)], prior, pvar)
        diff = lc - enc
        ad = jnp.abs(diff)
        sl1 = jnp.sum(jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5), axis=1)
        loss = loc_w * sl1 * fg + conf_w * ce * conf_weight
        if normalize:
            loss = loss / jnp.maximum(num_pos.astype(loss.dtype), 1.0)
        return loss

    out = jax.vmap(per_image)(loc, conf, gts, gtl, gt_num)
    return {"Loss": [out]}


@register("roi_perspective_transform", no_grad_inputs=("ROIs",))
def _roi_perspective_transform(ctx, ins, attrs):
    """Perspective-warp quadrilateral RoIs to a fixed grid
    (detection/roi_perspective_transform_op.cc): ROIs [R, 8] = 4 corners
    (x1 y1 ... x4 y4, clockwise from top-left), bilinear sampling."""
    x = ins["X"][0]  # [N, C, H, W]
    rois = ins["ROIs"][0]
    batch_idx = (
        ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
        if ins.get("RoisBatch")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    th = int(attrs.get("transformed_height", 8))
    tw = int(attrs.get("transformed_width", 8))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape

    def warp_one(quad, bi):
        q = quad.reshape(4, 2) * scale  # tl, tr, br, bl
        # bilinear interpolation of the quad surface (projective-lite:
        # exact for parallelograms, close for mild perspective)
        u = (jnp.arange(tw) + 0.5) / tw
        v = (jnp.arange(th) + 0.5) / th
        uu, vv = jnp.meshgrid(u, v)  # [th, tw]
        top = q[0][None, None, :] * (1 - uu[..., None]) + q[1][None, None, :] * uu[..., None]
        bot = q[3][None, None, :] * (1 - uu[..., None]) + q[2][None, None, :] * uu[..., None]
        pts = top * (1 - vv[..., None]) + bot * vv[..., None]  # [th, tw, 2]
        px, py = pts[..., 0], pts[..., 1]
        x0 = jnp.floor(px)
        y0 = jnp.floor(py)
        wx = px - x0
        wy = py - y0
        img = x[bi]

        def g(yy, xx):
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            return img[:, yc, xc]  # [C, th, tw]

        return (
            g(y0, x0) * (1 - wy) * (1 - wx)
            + g(y0, x0 + 1) * (1 - wy) * wx
            + g(y0 + 1, x0) * wy * (1 - wx)
            + g(y0 + 1, x0 + 1) * wy * wx
        )

    out = jax.vmap(warp_one)(rois, batch_idx)  # [R, C, th, tw]
    return {"Out": [out]}
