"""MQ2007 learning-to-rank readers (python/paddle/dataset/mq2007.py API
parity): LETOR 4.0 format, pointwise / pairwise / listwise modes.

Real data: DATA_HOME/MQ2007/{train,test}.txt lines
  <rel> qid:<q> 1:<f1> 2:<f2> ... #docid...
Otherwise deterministic synthetic queries with 46 features (the LETOR
feature count).
"""

import os

import numpy as np

from . import common

__all__ = ["train", "test"]

_N_FEAT = 46


def _parse_letor(path):
    """-> {qid: [(rel, feature_vector)]}"""
    queries = {}
    with open(path) as f:
        for ln in f:
            ln = ln.split("#")[0].strip()
            if not ln:
                continue
            parts = ln.split()
            rel = int(parts[0])
            qid = parts[1].split(":")[1]
            feats = np.zeros(_N_FEAT, "float32")
            for kv in parts[2:]:
                k, v = kv.split(":")
                idx = int(k) - 1
                if 0 <= idx < _N_FEAT:
                    feats[idx] = float(v)
            queries.setdefault(qid, []).append((rel, feats))
    return queries


def _synthetic(n_queries, seed):
    rng = np.random.RandomState(seed)
    queries = {}
    for q in range(n_queries):
        docs = []
        w = rng.rand(_N_FEAT)
        for _ in range(int(rng.randint(5, 15))):
            f = rng.rand(_N_FEAT).astype("float32")
            rel = int(np.clip(np.floor(f @ w / (_N_FEAT / 6.0)), 0, 2))
            docs.append((rel, f))
        queries["q%d" % q] = docs
    return queries


def _load(split, seed):
    path = common.data_path("MQ2007", split + ".txt")
    if os.path.exists(path):
        return _parse_letor(path)
    common.synthetic_note("mq2007")
    return _synthetic(60, seed)


def _reader(split, format, seed):
    def pointwise():
        qs = _load(split, seed)
        for qid in sorted(qs):
            for rel, f in qs[qid]:
                yield float(rel), f

    def pairwise():
        qs = _load(split, seed)
        for qid in sorted(qs):
            docs = qs[qid]
            for i, (ri, fi) in enumerate(docs):
                for rj, fj in docs[i + 1:]:
                    if ri > rj:
                        yield 1.0, fi, fj
                    elif rj > ri:
                        yield 1.0, fj, fi

    def listwise():
        qs = _load(split, seed)
        for qid in sorted(qs):
            rels = [float(r) for r, _ in qs[qid]]
            feats = [f for _, f in qs[qid]]
            yield rels, feats

    return {"pointwise": pointwise, "pairwise": pairwise, "listwise": listwise}[
        format
    ]


def train(format="pairwise"):
    return _reader("train", format, 23)


def test(format="pairwise"):
    return _reader("test", format, 24)
