"""Oxford-102 flowers readers (python/paddle/dataset/flowers.py API parity).

Real data: DATA_HOME/flowers/ with jpg images under jpg/ plus
imagelabels.mat + setid.mat (needs scipy for the .mat files).  Otherwise
deterministic synthetic images.  Samples: (flattened CHW float image in
[0,1], int label in [0, 102)).
"""

import os

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

_HW = 32  # synthetic fallback resolution (reference crops 224; models
# under test use small inputs — real data passes through untouched)


def _real_reader(split_key):
    base = common.data_path("flowers")

    def reader():
        from scipy.io import loadmat

        try:
            from PIL import Image
        except ImportError as e:  # fail loudly, not a silent empty epoch
            raise RuntimeError(
                "flowers: real data found under %s but Pillow is not "
                "installed (needed to decode jpgs)" % base
            ) from e

        labels = loadmat(os.path.join(base, "imagelabels.mat"))["labels"][0]
        setid = loadmat(os.path.join(base, "setid.mat"))
        ids = setid[split_key][0]
        for i in ids:
            path = os.path.join(base, "jpg", "image_%05d.jpg" % i)
            img = np.asarray(Image.open(path), dtype="float32") / 255.0
            yield img.transpose(2, 0, 1).ravel(), int(labels[i - 1]) - 1

    return reader


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % 102
            img = rng.rand(3 * _HW * _HW).astype("float32") * 0.2
            img[(label * 29) % (3 * _HW * _HW - 64):][:64] += 0.7
            yield img, label

    return reader


def _make(split_key, n, seed):
    if common.have_file("flowers", "imagelabels.mat"):
        return _real_reader(split_key)
    common.synthetic_note("flowers")
    return _synthetic(n, seed)


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _make("trnid", 1020, 31)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _make("tstid", 512, 32)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _make("valid", 256, 33)
