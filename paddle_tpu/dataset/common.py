"""Dataset infra (python/paddle/dataset/common.py analog).

The reference downloads real corpora with md5-checked caching.  This
environment is zero-egress, so: datasets load from the local cache dir when
the files are already present (same layout the reference uses, DATA_HOME),
and otherwise fall back to deterministic synthetic data with the correct
shapes/vocabulary so pipelines and models run end-to-end.  Swap in real data
by dropping files into DATA_HOME.
"""

import hashlib
import os


__all__ = ["DATA_HOME", "md5file", "data_path", "have_file", "synthetic_note"]

DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def have_file(*parts):
    return os.path.exists(data_path(*parts))


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


_warned = set()


def synthetic_note(name):
    if name not in _warned:
        _warned.add(name)
        import sys

        print(
            "[paddle_tpu.dataset] %s: no local data under %s — serving "
            "deterministic synthetic samples (zero-egress environment)"
            % (name, DATA_HOME),
            file=sys.stderr,
        )
