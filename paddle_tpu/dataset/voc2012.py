"""Pascal VOC2012 segmentation readers (python/paddle/dataset/voc2012.py API
parity).

Real data: DATA_HOME/voc2012/VOCdevkit/VOC2012/ standard layout (JPEGImages,
SegmentationClass, ImageSets/Segmentation/*.txt).  Otherwise deterministic
synthetic (image, segmentation mask) pairs: image CHW float32, mask HW int32
with 21 classes (20 + background).
"""

import os

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

_HW = 24
_N_CLASSES = 21


def _real_reader(split):
    root = common.data_path("voc2012", "VOCdevkit", "VOC2012")

    def reader():
        from PIL import Image

        lst = os.path.join(root, "ImageSets", "Segmentation", split + ".txt")
        with open(lst) as f:
            names = [ln.strip() for ln in f if ln.strip()]
        for name in names:
            img = np.asarray(
                Image.open(os.path.join(root, "JPEGImages", name + ".jpg")),
                dtype="float32",
            ) / 255.0
            seg = np.asarray(
                Image.open(
                    os.path.join(root, "SegmentationClass", name + ".png")
                ),
                dtype="int32",
            )
            yield img.transpose(2, 0, 1), seg

    return reader


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            img = rng.rand(3, _HW, _HW).astype("float32")
            seg = np.zeros((_HW, _HW), "int32")
            c = i % (_N_CLASSES - 1) + 1
            y, x = (i * 7) % (_HW - 8), (i * 11) % (_HW - 8)
            seg[y:y + 8, x:x + 8] = c
            yield img, seg

    return reader


def _make(split, n, seed):
    if common.have_file("voc2012", "VOCdevkit", "VOC2012", "ImageSets",
                        "Segmentation", split + ".txt"):
        return _real_reader(split)
    common.synthetic_note("voc2012")
    return _synthetic(n, seed)


def train():
    return _make("train", 400, 41)


def val():
    return _make("val", 100, 42)


def test():
    return _make("val", 100, 43)
