"""IMDB sentiment reader (python/paddle/dataset/imdb.py parity): word-id
sequences + binary label."""

import numpy as np

from . import common

__all__ = ["train", "test", "word_dict"]

_VOCAB = 5147  # mirrors the reference's imdb.word_dict() size magnitude


def word_dict():
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % 2
            length = rng.randint(8, 64)
            # class-dependent token distribution
            lo, hi = (0, _VOCAB // 2) if label == 0 else (_VOCAB // 2, _VOCAB)
            ids = rng.randint(lo, hi, (length,)).tolist()
            yield ids, int(label)

    return reader


def train(word_idx=None):
    common.synthetic_note("imdb")
    return _synthetic(2000, 0)


def test(word_idx=None):
    common.synthetic_note("imdb")
    return _synthetic(400, 1)
