"""UCI housing regression reader (python/paddle/dataset/uci_housing.py
parity): 13 features -> price."""

import numpy as np

from . import common

__all__ = ["train", "test", "feature_num"]

feature_num = 13


def _load():
    path = common.data_path("uci_housing", "housing.data")
    if common.have_file("uci_housing", "housing.data"):
        data = np.loadtxt(path)
    else:
        common.synthetic_note("uci_housing")
        rng = np.random.RandomState(0)
        x = rng.rand(506, feature_num)
        w = rng.rand(feature_num)
        y = x @ w * 10 + rng.randn(506) * 0.5 + 10
        data = np.concatenate([x, y[:, None]], axis=1)
    feats = data[:, :-1]
    feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-6)
    return feats.astype("float32"), data[:, -1:].astype("float32")


def train():
    def reader():
        x, y = _load()
        n = int(len(x) * 0.8)
        for i in range(n):
            yield x[i], y[i]

    return reader


def test():
    def reader():
        x, y = _load()
        n = int(len(x) * 0.8)
        for i in range(n, len(x)):
            yield x[i], y[i]

    return reader
