"""MNIST reader (python/paddle/dataset/mnist.py API parity).

Loads the standard idx-format files from DATA_HOME/mnist when present;
otherwise serves synthetic digit-like samples (see common.py)."""

import gzip
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]


def _read_idx_images(path):
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    return data.astype("float32") / 127.5 - 1.0


def _read_idx_labels(path):
    with gzip.open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype("int64")


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % 10
            img = rng.rand(784).astype("float32") * 0.1 - 1.0
            # a crude class-dependent blob so models can actually learn
            img[label * 70 : label * 70 + 70] += 1.5
            yield img, int(label)

    return reader


def _reader(images_file, labels_file, n_synth, seed):
    img_path = common.data_path("mnist", images_file)
    lbl_path = common.data_path("mnist", labels_file)
    if common.have_file("mnist", images_file) and common.have_file("mnist", labels_file):
        def reader():
            images = _read_idx_images(img_path)
            labels = _read_idx_labels(lbl_path)
            for img, lbl in zip(images, labels):
                yield img, int(lbl)

        return reader
    common.synthetic_note("mnist")
    return _synthetic(n_synth, seed)


def train():
    return _reader("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz", 6000, 0)


def test():
    return _reader("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz", 1000, 1)
