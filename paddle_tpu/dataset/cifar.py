"""CIFAR-10/100 readers (python/paddle/dataset/cifar.py API parity)."""

import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]


def _tar_reader(tar_name, sub_names):
    path = common.data_path("cifar", tar_name)

    def reader():
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not any(s in member.name for s in sub_names):
                    continue
                batch = pickle.load(tf.extractfile(member), encoding="latin1")
                data = batch["data"].astype("float32") / 127.5 - 1.0
                labels = batch.get("labels", batch.get("fine_labels"))
                for row, lbl in zip(data, labels):
                    yield row, int(lbl)

    return reader


def _synthetic(n_classes, n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % n_classes
            img = rng.rand(3072).astype("float32") * 0.2 - 1.0
            img[(label * 293) % 2800 : (label * 293) % 2800 + 200] += 1.2
            yield img, int(label)

    return reader


def _make(tar_name, subs, n_classes, n_synth, seed):
    if common.have_file("cifar", tar_name):
        return _tar_reader(tar_name, subs)
    common.synthetic_note("cifar")
    return _synthetic(n_classes, n_synth, seed)


def train10():
    return _make("cifar-10-python.tar.gz", ["data_batch"], 10, 5000, 0)


def test10():
    return _make("cifar-10-python.tar.gz", ["test_batch"], 10, 1000, 1)


def train100():
    return _make("cifar-100-python.tar.gz", ["train"], 100, 5000, 2)


def test100():
    return _make("cifar-100-python.tar.gz", ["test"], 100, 1000, 3)
