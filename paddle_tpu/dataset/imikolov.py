"""PTB/imikolov n-gram LM reader (python/paddle/dataset/imikolov.py parity)."""

import numpy as np

from . import common

__all__ = ["train", "test", "build_dict"]

_VOCAB = 2074


def build_dict(min_word_freq=50):
    return {("w%d" % i): i for i in range(_VOCAB)}


def _synthetic(n, window, seed):
    rng = np.random.RandomState(seed)

    def reader():
        # markov-ish chain so embeddings have learnable structure
        state = rng.randint(0, _VOCAB)
        for _ in range(n):
            seq = []
            for _ in range(window):
                state = (state * 31 + rng.randint(0, 7)) % _VOCAB
                seq.append(state)
            yield tuple(seq)

    return reader


def train(word_idx=None, n=5):
    common.synthetic_note("imikolov")
    return _synthetic(4000, n, 0)


def test(word_idx=None, n=5):
    common.synthetic_note("imikolov")
    return _synthetic(800, n, 1)
