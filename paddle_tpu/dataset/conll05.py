"""CoNLL-2005 SRL readers (python/paddle/dataset/conll05.py API parity).

Real data: word/verb/target dicts + the test corpus under
DATA_HOME/conll05st/.  Otherwise deterministic synthetic SRL sequences with
the reference's 9-slot sample layout: (word, ctx_n2, ctx_n1, ctx_0, ctx_p1,
ctx_p2, verb, mark, label) index lists.
"""

import gzip
import os

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test"]

UNK_IDX = 0

_state = {}


def _load_dict(path):
    d = {}
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        for i, ln in enumerate(f):
            d[ln.strip()] = i
    return d


def _load():
    if _state:
        return _state
    base = common.data_path("conll05st")
    wd = os.path.join(base, "wordDict.txt")
    vd = os.path.join(base, "verbDict.txt")
    td = os.path.join(base, "targetDict.txt")
    if os.path.exists(wd):
        word_dict = _load_dict(wd)
        verb_dict = _load_dict(vd)
        label_dict = _load_dict(td)
    else:
        common.synthetic_note("conll05")
        word_dict = {"w%d" % i: i for i in range(200)}
        verb_dict = {"v%d" % i: i for i in range(20)}
        label_dict = {
            l: i
            for i, l in enumerate(
                ["O", "B-A0", "I-A0", "B-A1", "I-A1", "B-V", "I-V"]
            )
        }
    _state.update(word=word_dict, verb=verb_dict, label=label_dict)
    return _state


def get_dict():
    """Returns (word_dict, verb_dict, label_dict)."""
    st = _load()
    return st["word"], st["verb"], st["label"]


def get_embedding():
    """Pretrained word embedding table [len(word_dict), 32] (the reference
    ships emb.gz; synthetic mode derives a deterministic table)."""
    st = _load()
    path = common.data_path("conll05st", "emb")
    if os.path.exists(path):
        return np.loadtxt(path, dtype="float32")
    rng = np.random.RandomState(11)
    return rng.normal(0, 0.1, (len(st["word"]), 32)).astype("float32")


def _parse_props_corpus(words_path, props_path):
    """CoNLL-2005 words+props format -> one (tokens, pred_pos, iob_labels)
    sample per (sentence, predicate).  Props columns hold span-parenthesis
    tags like '(A0*', '*', '*)' per predicate."""

    def lines(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rt") as f:
            sent = []
            for ln in f:
                ln = ln.strip()
                if not ln:
                    if sent:
                        yield sent
                        sent = []
                    continue
                sent.append(ln.split())
            if sent:
                yield sent

    for wsent, psent in zip(lines(words_path), lines(props_path)):
        tokens = [w[0] for w in wsent]
        n_pred = len(psent[0]) - 1
        preds = [row[0] for row in psent]
        for k in range(n_pred):
            labels = []
            cur = None
            pred_pos = 0
            for i, row in enumerate(psent):
                tag = row[1 + k]
                if tag.startswith("("):
                    cur = tag.strip("()*")
                    labels.append("B-" + cur)
                    if cur == "V":
                        pred_pos = i
                elif cur is not None:
                    labels.append("I-" + cur)
                else:
                    labels.append("O")
                if tag.endswith(")"):
                    cur = None
            if preds[pred_pos] == "-" and "V" not in [l[2:] for l in labels]:
                continue
            yield tokens, pred_pos, labels


def test():
    """Reader over (word, 5 ctx windows, verb, mark, label) id sequences.
    Parses the real corpus (test.wsj.words + test.wsj.props under
    DATA_HOME/conll05st/) when present; synthetic otherwise."""

    st = _load()
    words_path = None
    for cand in ("test.wsj.words", "test.wsj.words.gz"):
        p = common.data_path("conll05st", cand)
        if os.path.exists(p):
            words_path = p
            break
    if words_path is not None:
        props_path = words_path.replace(".words", ".props")

        def reader():
            wd, vd, ld = st["word"], st["verb"], st["label"]
            for tokens, pred_pos, labels in _parse_props_corpus(
                words_path, props_path
            ):
                n = len(tokens)
                ids = [wd.get(t.lower(), UNK_IDX) for t in tokens]

                def ctx(off):
                    j = pred_pos + off
                    return ids[j] if 0 <= j < n else UNK_IDX

                verb = vd.get(tokens[pred_pos].lower(), 0)
                yield (
                    ids,
                    [ctx(-2)] * n,
                    [ctx(-1)] * n,
                    [ctx(0)] * n,
                    [ctx(1)] * n,
                    [ctx(2)] * n,
                    [verb] * n,
                    [1 if i == pred_pos else 0 for i in range(n)],
                    [ld.get(l, 0) for l in labels],
                )

        return reader

    def reader():
        st = _load()
        nw = len(st["word"])
        nv = len(st["verb"])
        nl = len(st["label"])
        rng = np.random.RandomState(13)
        for _ in range(200):
            n = int(rng.randint(4, 12))
            words = rng.randint(0, nw, n).tolist()
            pred_pos = int(rng.randint(0, n))
            verb = int(rng.randint(0, nv))

            def ctx(off):
                j = pred_pos + off
                return words[j] if 0 <= j < n else UNK_IDX

            labels = []
            for i in range(n):
                if i == pred_pos:
                    labels.append(st["label"].get("B-V", 0))
                else:
                    labels.append(int(rng.randint(0, nl)))
            mark = [1 if i == pred_pos else 0 for i in range(n)]
            yield (
                words,
                [ctx(-2)] * n,
                [ctx(-1)] * n,
                [ctx(0)] * n,
                [ctx(1)] * n,
                [ctx(2)] * n,
                [verb] * n,
                mark,
                labels,
            )

    return reader
