"""WMT16 en-de seq2seq reader (python/paddle/dataset/wmt16.py parity):
(src_ids, trg_ids, trg_next_ids) triples."""

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]


def get_dict(lang, dict_size, reverse=False):
    d = {("%s_w%d" % (lang, i)): i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _synthetic(n, src_vocab, trg_vocab, seed):
    rng = np.random.RandomState(seed)
    bos, eos = 0, 1

    def reader():
        for _ in range(n):
            slen = rng.randint(4, 30)
            src = rng.randint(2, src_vocab, (slen,)).tolist()
            # "translation": deterministic mapping + length jitter
            trg = [(t * 7 + 3) % (trg_vocab - 2) + 2 for t in src][: max(3, slen - 2)]
            yield src, [bos] + trg, trg + [eos]

    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    common.synthetic_note("wmt16")
    return _synthetic(4000, src_dict_size, trg_dict_size, 0)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    common.synthetic_note("wmt16")
    return _synthetic(500, src_dict_size, trg_dict_size, 1)
