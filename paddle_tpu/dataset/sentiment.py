"""Movie-review sentiment readers (python/paddle/dataset/sentiment.py API
parity — the reference wraps NLTK's movie_reviews corpus).

Real data: pos/neg review text files under DATA_HOME/sentiment/{pos,neg}/.
Otherwise deterministic synthetic reviews over a small polarity-biased
vocabulary.  Samples: (word index list, label) with label 0=positive,
1=negative (reference convention).
"""

import os

import numpy as np

from . import common

__all__ = ["get_word_dict", "train", "test"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_state = {}


def _load():
    if _state:
        return _state
    base = common.data_path("sentiment")
    docs = []  # (words, label)
    if os.path.isdir(os.path.join(base, "pos")):
        for label, sub in ((0, "pos"), (1, "neg")):
            d = os.path.join(base, sub)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), errors="ignore") as f:
                    docs.append((f.read().lower().split(), label))
        # deterministic shuffle so the index-based train/test split mixes
        # classes (raw layout is all-pos-then-all-neg)
        np.random.RandomState(2388).shuffle(docs)
    else:
        common.synthetic_note("sentiment")
        rng = np.random.RandomState(17)
        pos_words = ["good", "great", "fine", "superb", "nice"]
        neg_words = ["bad", "awful", "poor", "boring", "worse"]
        neutral = ["movie", "plot", "actor", "scene", "film", "the", "a"]
        for i in range(NUM_TOTAL_INSTANCES):
            label = i % 2
            bias = neg_words if label else pos_words
            n = int(rng.randint(5, 30))
            words = []
            for _ in range(n):
                pool = bias if rng.rand() < 0.4 else neutral
                words.append(pool[int(rng.randint(0, len(pool)))])
            docs.append((words, label))
        rng.shuffle(docs)
    freq = {}
    for words, _ in docs:
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    word_dict = {w: i for i, (w, _) in enumerate(ordered)}
    _state.update(docs=docs, word_dict=word_dict)
    return _state


def get_word_dict():
    """word -> index sorted by corpus frequency (reference contract)."""
    return _load()["word_dict"]


def _reader(lo, hi):
    def reader():
        st = _load()
        wd = st["word_dict"]
        for words, label in st["docs"][lo:hi]:
            yield [wd[w] for w in words if w in wd], label

    return reader


def train():
    return _reader(0, NUM_TRAINING_INSTANCES)


def test():
    return _reader(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES)
