"""paddle_tpu.dataset"""
