"""Dataset loaders (python/paddle/dataset API parity): local-cache loading
with deterministic synthetic fallback (zero-egress; see common.py)."""

from . import common, mnist, cifar, uci_housing, imdb, imikolov, wmt16
from . import movielens, conll05, sentiment, flowers, voc2012, wmt14, mq2007
