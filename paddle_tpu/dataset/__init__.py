"""Dataset loaders (python/paddle/dataset API parity): local-cache loading
with deterministic synthetic fallback (zero-egress; see common.py)."""

from . import common, mnist, cifar, uci_housing, imdb, imikolov, wmt16
