"""WMT14 en-fr readers (python/paddle/dataset/wmt14.py API parity).

Real data: DATA_HOME/wmt14/ with src.dict, trg.dict and train/test files of
tab-separated parallel sentences.  Otherwise deterministic synthetic
parallel id sequences.  Samples: (src_ids, trg_ids_with_<s>, trg_ids_with_<e>)
— the reference's (source, target-input, target-label) triple.
"""

import os

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2

_state = {}


def _load(dict_size):
    key = int(dict_size)
    if key in _state:
        return _state[key]
    base = common.data_path("wmt14")
    if os.path.exists(os.path.join(base, "src.dict")):
        def rd(fn):
            d = {}
            with open(os.path.join(base, fn), encoding="utf-8") as f:
                for i, ln in enumerate(f):
                    if i >= dict_size:
                        break
                    d[ln.strip()] = i
            return d

        src_dict, trg_dict = rd("src.dict"), rd("trg.dict")

        def rd_pairs(fn):
            out = []
            path = os.path.join(base, fn)
            if not os.path.exists(path):
                return None
            with open(path, encoding="utf-8") as f:
                for ln in f:
                    parts = ln.rstrip("\n").split("\t")
                    if len(parts) == 2:
                        out.append((parts[0].split(), parts[1].split()))
            return out

        pairs = rd_pairs("train")
        if pairs is None:  # dicts without corpus = broken download: be loud
            raise FileNotFoundError(
                "wmt14: dictionaries found under %s but no 'train' file" % base
            )
        test_pairs = rd_pairs("test")  # real held-out set when shipped
    else:
        common.synthetic_note("wmt14")
        src_dict = {START: 0, END: 1, UNK: 2}
        trg_dict = {START: 0, END: 1, UNK: 2}
        for i in range(3, dict_size):
            src_dict["src%d" % i] = i
            trg_dict["trg%d" % i] = i
        rng = np.random.RandomState(19)
        pairs = []
        inv_s = list(src_dict)
        inv_t = list(trg_dict)
        for _ in range(500):
            n = int(rng.randint(3, 10))
            s = [inv_s[int(rng.randint(3, len(inv_s)))] for _ in range(n)]
            t = [inv_t[int(rng.randint(3, len(inv_t)))] for _ in range(n)]
            pairs.append((s, t))
        test_pairs = None
    _state[key] = (src_dict, trg_dict, pairs, test_pairs)
    return _state[key]


def _reader(dict_size, is_test):
    def reader():
        src_dict, trg_dict, pairs, test_pairs = _load(dict_size)
        if test_pairs is not None:
            # real split files: train serves the whole train file, test the
            # shipped held-out set (no leakage)
            it = test_pairs if is_test else pairs
            split = ((s, t) for s, t in it)
        else:
            split = (
                (s, t)
                for i, (s, t) in enumerate(pairs)
                if (i % 10 == 0) == is_test
            )
        for s, t in split:
            src_ids = [src_dict.get(w, UNK_ID) for w in s]
            t_ids = [trg_dict.get(w, UNK_ID) for w in t]
            yield src_ids, [START_ID] + t_ids, t_ids + [END_ID]

    return reader


def train(dict_size=30000):
    return _reader(dict_size, False)


def test(dict_size=30000):
    return _reader(dict_size, True)


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict); reverse=True flips to id->word."""
    src_dict, trg_dict, _pairs, _test = _load(dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
