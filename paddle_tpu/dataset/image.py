"""Image preprocessing helpers (python/paddle/dataset/image.py parity).

The reference shells out to cv2 for decode/resize; here decode uses PIL
when available (cv2/PIL are IO conveniences, not framework core) and the
geometric transforms are pure numpy, so the training-path functions
(resize_short, crops, flip, to_chw, simple_transform) work in any
environment.  Interpolation is bilinear via numpy gather — host-side prep
work; on-device resize lives in the bilinear_interp/nearest_interp ops.
"""

import numpy as np

__all__ = [
    "load_image",
    "load_image_bytes",
    "resize_short",
    "to_chw",
    "center_crop",
    "random_crop",
    "left_right_flip",
    "simple_transform",
    "load_and_transform",
]


def _decode(data):
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL present in this env
        raise RuntimeError(
            "image decode needs PIL (install pillow) — the numpy transforms "
            "below work on already-decoded arrays"
        ) from e
    return Image.open(io.BytesIO(data))


def load_image_bytes(data, is_color=True):
    """Decode an encoded image buffer to HWC uint8 (RGB) or HW (gray)."""
    img = _decode(data)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(path, is_color=True):
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _bilinear_resize(im, out_h, out_w):
    """Pure-numpy bilinear resize over the first two (H, W) axes."""
    h, w = im.shape[:2]
    if (h, w) == (out_h, out_w):
        return im
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)
    wx = np.clip(xs - x0, 0.0, 1.0)
    trail = (1,) * (im.ndim - 2)  # broadcast over an optional channel axis
    wx_row = wx.reshape((1, -1) + trail)
    wy_col = wy.reshape((-1, 1) + trail)
    # gather the needed source rows FIRST, then convert — the float copy
    # is [out_h, w, C], never the full source image
    rows0 = im[y0].astype(np.float64)
    rows1 = im[y1].astype(np.float64)
    top = rows0[:, x0] * (1 - wx_row) + rows0[:, x1] * wx_row
    bot = rows1[:, x0] * (1 - wx_row) + rows1[:, x1] * wx_row
    out = top * (1 - wy_col) + bot * wy_col
    if np.issubdtype(im.dtype, np.integer):
        return np.rint(out).astype(im.dtype)  # round, don't truncate-darken
    return out


def resize_short(im, size):
    """Scale so the SHORTER edge becomes `size` (aspect preserved)."""
    h, w = im.shape[:2]
    if h < w:
        return _bilinear_resize(im, size, int(round(w * size / h)))
    return _bilinear_resize(im, int(round(h * size / w)), size)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (the framework's conv layout)."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = rng.randint(0, h - size + 1)
    w_start = rng.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> (random|center) crop -> (train) random flip ->
    CHW float32 -> optional mean subtraction (per-channel or full array)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        rng_ = rng or np.random
        if rng_.randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.asarray(mean, "float32")
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(
        load_image(filename, is_color), resize_size, crop_size, is_train,
        is_color, mean,
    )
