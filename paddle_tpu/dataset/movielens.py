"""MovieLens-1M readers (python/paddle/dataset/movielens.py API parity).

Real data: drop ml-1m.zip's extracted files under DATA_HOME/movielens/ml-1m/
(movies.dat, users.dat, ratings.dat, '::'-separated).  Otherwise serves
deterministic synthetic samples with the reference's feature layout:
[user_id, gender, age, job, movie_id, categories, title] -> rating.
"""

import os
import re

import numpy as np

from . import common

__all__ = [
    "train",
    "test",
    "get_movie_title_dict",
    "max_movie_id",
    "max_user_id",
    "max_job_id",
    "age_table",
    "movie_categories",
    "user_info",
    "movie_info",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, cat_dict, title_dict):
        return [
            self.index,
            [cat_dict[c] for c in self.categories],
            [title_dict[w] for w in self.title.split()],
        ]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


_state = {}


def _load():
    if _state:
        return _state
    base = common.data_path("movielens", "ml-1m")
    movies, users, ratings = {}, {}, []
    if os.path.exists(os.path.join(base, "ratings.dat")):
        pat = re.compile(r"[^\w\s]")
        with open(os.path.join(base, "movies.dat"), encoding="latin1") as f:
            for ln in f:
                mid, title, cats = ln.strip().split("::")
                title = pat.sub("", title.lower())
                movies[int(mid)] = MovieInfo(mid, cats.split("|"), title)
        with open(os.path.join(base, "users.dat"), encoding="latin1") as f:
            for ln in f:
                uid, gender, age, job, _zip = ln.strip().split("::")
                users[int(uid)] = UserInfo(uid, gender, age, job)
        with open(os.path.join(base, "ratings.dat"), encoding="latin1") as f:
            for ln in f:
                uid, mid, rating, _ts = ln.strip().split("::")
                ratings.append((int(uid), int(mid), float(rating)))
    else:
        common.synthetic_note("movielens")
        rng = np.random.RandomState(7)
        for mid in range(1, 201):
            cats = [_CATEGORIES[mid % len(_CATEGORIES)]]
            movies[mid] = MovieInfo(mid, cats, "title %d word%d" % (mid, mid % 37))
        for uid in range(1, 101):
            users[uid] = UserInfo(
                uid, "M" if uid % 2 else "F", age_table[uid % 7], uid % 21
            )
        for _ in range(4000):
            uid = int(rng.randint(1, 101))
            mid = int(rng.randint(1, 201))
            ratings.append((uid, mid, float(rng.randint(1, 6))))
    cat_dict = {c: i for i, c in enumerate(_CATEGORIES)}
    words = sorted({w for m in movies.values() for w in m.title.split()})
    title_dict = {w: i for i, w in enumerate(words)}
    _state.update(
        movies=movies, users=users, ratings=ratings,
        cat_dict=cat_dict, title_dict=title_dict,
    )
    return _state


def _reader(is_test):
    def reader():
        st = _load()
        for i, (uid, mid, rating) in enumerate(st["ratings"]):
            in_test = i % 10 == 0
            if in_test != is_test:
                continue
            usr = st["users"][uid].value()
            mov = st["movies"][mid].value(st["cat_dict"], st["title_dict"])
            yield usr + mov + [[rating]]

    return reader


def train():
    return _reader(False)


def test():
    return _reader(True)


def get_movie_title_dict():
    return _load()["title_dict"]


def movie_categories():
    return _load()["cat_dict"]


def max_movie_id():
    return max(_load()["movies"])


def max_user_id():
    return max(_load()["users"])


def max_job_id():
    return max(u.job_id for u in _load()["users"].values())


def user_info():
    return list(_load()["users"].values())


def movie_info():
    return list(_load()["movies"].values())
