"""Prefix-cache KV reuse for the serving engine: a KV ROW POOL of
registered common prompt prefixes (shared system prompts, few-shot
templates) plus the compiled copy programs that move whole cache rows
between the prefix pool and the slot pool.

Design (the ROADMAP item 4 "radix/prefix KV reuse" lever, flattened to
the common case):

- The pool holds R rows per cache persistable, named ``pfx_<cache>``
  ([R, n_kv, t_max, dh] — the slot pool's shape with R rows).  The
  names keep the ``_{k,v}cache_<layer>`` suffix, so the GSPMD partition
  rule that shards slot caches on the heads axis shards the prefix pool
  identically — the row copy is then a per-shard copy with no
  resharding.
- Matching is HOST-side on token ids: longest common prefix between a
  request's prompt and each registered row, floored to a multiple of
  ``chunk`` and capped at prompt_len - 1 (at least one real token must
  go through prefill to produce the first logits).  The chunk floor is
  what makes prefix-hit streams BIT-identical to cold streams: the
  engine prefills in width-W chunks from position 0, so resuming at a
  multiple of W replays the exact chunk schedule a cold run would have
  used from that boundary on (same feed values, same writes, same
  logits bytes).
- Copying is DEVICE-side through one compiled program per direction
  (decode_cache.make_row_copy_program, the slot-reset program
  generalized to gathers): load = prefix rows -> admitted slots' rows,
  store = a freshly prefilled slot's rows -> a prefix row.  Row ids and
  masks are feeds, so any assignment reuses the one executable — the
  zero-retrace serving contract extends to prefix traffic.
- A speculative engine registers a second BANK over the draft model's
  caches: with spec + sampling + prefix all on, the draft distribution
  must also resume bit-exactly, or accept/reject draws fork the stream.

Invalidation: rows are invalidated by re-registration (same tokens
dedup to the same row; new tokens evict the least-recently-matched row
when full).  Weights changing invalidates everything — call
``invalidate()`` (drops the host index; stale KV rows are never matched
again and get overwritten by later registrations)."""

import numpy as np

__all__ = ["PrefixCache"]


class _Bank:
    """One cache family's copy machinery (target bank, draft bank)."""

    __slots__ = ("load_prog", "store_prog", "startup", "scope", "tag")

    def __init__(self, load_prog, store_prog, startup, scope, tag):
        self.load_prog = load_prog
        self.store_prog = store_prog
        self.startup = startup
        self.scope = scope
        self.tag = tag


class PrefixCache:
    """Host index + compiled copy programs for prefix KV reuse.

    rows:  prefix pool capacity (registered prefixes resident at once)
    chunk: match granularity — MUST be a multiple of the engine's
           dispatch width W (the engine enforces ==/multiple), so a
           resumed prefill replays the cold chunk schedule exactly
    """

    def __init__(self, rows, chunk):
        self.rows = int(rows)
        self.chunk = int(chunk)
        assert self.rows >= 1 and self.chunk >= 1, (rows, chunk)
        self._tokens = [None] * self.rows  # np int64 arrays (host index)
        self._tick = 0
        self._last_used = [-1] * self.rows
        self._banks = []
        # lifetime counters (the engine's per-episode counters reset per
        # run; these survive across runs for the control plane)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.registrations = 0
        self.evictions = 0

    # -- bank wiring (engine-side setup) --------------------------------

    def add_bank(self, cache_names, slot_shape, dtype, tag="target",
                 scope=None):
        """Build the prefix persistables + load/store/startup programs
        for one cache family.  cache_names: the slot-pool persistable
        names ([B, n_kv, t_max, dh] each, shape == slot_shape); the
        prefix twins are ``pfx_<name>`` with R rows.  `scope`: the
        fluid.Scope the family lives in (None = ambient scope — the
        target family and self-draft; a separate-scope draft passes its
        own).  Returns the bank (also retained internally)."""
        import paddle_tpu as fluid
        from ..models.decode_cache import (add_cache_zero_fills,
                                           make_row_copy_program)

        b = int(slot_shape[0])
        tail = list(slot_shape[1:])
        pfx_shape = [self.rows] + tail
        startup = fluid.Program()
        add_cache_zero_fills(
            startup, [("pfx_" + n, pfx_shape) for n in cache_names],
            dtype=dtype)
        load_prog = make_row_copy_program(
            [("pfx_" + n, pfx_shape, n, list(slot_shape))
             for n in cache_names], b, dtype=dtype)
        store_prog = make_row_copy_program(
            [(n, list(slot_shape), "pfx_" + n, pfx_shape)
             for n in cache_names], self.rows, dtype=dtype)
        bank = _Bank(load_prog, store_prog, startup, scope, tag)
        self._banks.append(bank)
        return bank

    @property
    def banks(self):
        return list(self._banks)

    def startup(self, exe):
        """Zero-fill every bank's prefix pool (run once at engine
        construction — NOT per engine.run(): registered rows persist
        across serving episodes)."""
        for bank in self._banks:
            exe.run(bank.startup, feed={}, fetch_list=[],
                    scope=bank.scope)

    # -- host index -----------------------------------------------------

    def match(self, prompt):
        """Longest-match against the registered rows: returns (row, L)
        with L a positive multiple of `chunk` (capped at len(prompt)-1),
        or (None, 0) on a miss.  Ties prefer the lower row id —
        deterministic, traffic-independent."""
        prompt = np.asarray(prompt, "int64").reshape(-1)
        best_row, best_len = None, 0
        for r, toks in enumerate(self._tokens):
            if toks is None:
                continue
            n = min(int(toks.size), int(prompt.size) - 1)
            if n < self.chunk:
                continue
            eq = prompt[:n] == toks[:n]
            lcp = n if eq.all() else int(np.argmax(~eq))
            length = (lcp // self.chunk) * self.chunk
            if length > best_len:
                best_row, best_len = r, length
        if best_len >= self.chunk:
            return best_row, best_len
        return None, 0

    def touch(self, row, reused_tokens):
        """Record a hit on `row` (LRU bump + counters)."""
        self._tick += 1
        self._last_used[row] = self._tick
        self.hits += 1
        self.tokens_reused += int(reused_tokens)

    def miss(self):
        self.misses += 1

    def assign(self, tokens):
        """Pick the row for `tokens` (already chunk-floored): an exact
        resident match reuses its row (returns (row, False) — KV bytes
        already present), else a free row, else the LRU row is evicted.
        Returns (row, fresh)."""
        tokens = np.asarray(tokens, "int64").reshape(-1)
        for r, toks in enumerate(self._tokens):
            if toks is not None and toks.size == tokens.size \
                    and bool((toks == tokens).all()):
                self._tick += 1
                self._last_used[r] = self._tick
                return r, False
        for r, toks in enumerate(self._tokens):
            if toks is None:
                row = r
                break
        else:
            row = min(range(self.rows), key=lambda r: self._last_used[r])
            self.evictions += 1
        self._tokens[row] = tokens.copy()
        self._tick += 1
        self._last_used[row] = self._tick
        self.registrations += 1
        return row, True

    def invalidate(self):
        """Drop the host index (e.g. after a weight update): stale KV
        rows are never matched again."""
        self._tokens = [None] * self.rows
        self._last_used = [-1] * self.rows

    def registered(self):
        """The resident prefixes as {row: token array} (diagnostics)."""
        return {r: t.copy() for r, t in enumerate(self._tokens)
                if t is not None}

    # -- device copies --------------------------------------------------

    def load(self, exe, slot_rows):
        """Copy prefix rows into slot rows: slot_rows = {slot: prefix
        row} for this admission wave.  One dispatch per bank, any
        assignment (the ids/masks are feeds)."""
        if not slot_rows or not self._banks:
            return
        b = int(self._banks[0].load_prog.global_block()
                .vars["copy_take"].shape[0])
        src = np.zeros(b, "int64")
        take = np.zeros(b, "float32")
        for slot, row in slot_rows.items():
            src[slot] = row
            take[slot] = 1.0
        feed = {"copy_src_rows": src, "copy_take": take,
                "copy_keep": 1.0 - take}
        for bank in self._banks:
            exe.run(bank.load_prog, feed=feed, fetch_list=[],
                    scope=bank.scope)

    def store(self, exe, row, slot):
        """Copy slot `slot`'s freshly prefilled cache rows into prefix
        row `row` (the registration step), every bank."""
        src = np.full(self.rows, int(slot), "int64")
        take = np.zeros(self.rows, "float32")
        take[row] = 1.0
        feed = {"copy_src_rows": src, "copy_take": take,
                "copy_keep": 1.0 - take}
        for bank in self._banks:
            exe.run(bank.store_prog, feed=feed, fetch_list=[],
                    scope=bank.scope)

    # -- reporting ------------------------------------------------------

    def counters(self):
        return {"prefix_lifetime_hits": self.hits,
                "prefix_lifetime_misses": self.misses,
                "prefix_lifetime_tokens_reused": self.tokens_reused,
                "prefix_registrations": self.registrations,
                "prefix_evictions": self.evictions,
                "prefix_rows": self.rows,
                "prefix_rows_used": sum(
                    1 for t in self._tokens if t is not None),
                "prefix_chunk": self.chunk}
