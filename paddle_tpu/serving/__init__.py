"""Continuous-batching LLM serving (docs/SERVING.md §5-§8).

The production serving front-end over the decode-cache stack: a
request scheduler (engine.ServingEngine) drives ONE compiled ragged
wide-step program over a slot-based KV-cache pool — admission,
interleaved prefill/decode, per-request sampling params, immediate
eviction — with every request's token stream bit-identical to its
solo run.  The decode/prefill fast path rides inside the same loop:
in-pool speculative decoding (a draft model's ragged step over the
same slot layout, one widened target dispatch verifying anchor+drafts
per slot) and prefix-cache KV reuse (prefix.PrefixCache — registered
common prompt prefixes copied row-wise into admitted slots so prefill
starts at the match boundary).  router.FabricRouter is the multi-pool
front door: sticky placement over N engine pools, fabric-wide
backpressure, drain-and-retire, and prefix-replay failover that
extends the exactness contract across pool death.
trace.make_poisson_trace / make_prefix_trace generate the seeded
open-loop bench/test workloads.
"""

from .engine import ServingEngine, serve_one_at_a_time
from .pool import SlotPool
from .pool_worker import spawn_pool_worker
from .prefix import PrefixCache
from .router import FabricRouter, ProcessPool, parse_pool_schedule
from .trace import Request, make_poisson_trace, make_prefix_trace

__all__ = ["ServingEngine", "serve_one_at_a_time", "SlotPool",
           "FabricRouter", "ProcessPool", "parse_pool_schedule",
           "spawn_pool_worker", "Request", "make_poisson_trace",
           "make_prefix_trace", "PrefixCache"]
