"""Continuous-batching LLM serving (docs/SERVING.md §5-§7).

The production serving front-end over the decode-cache stack: a
request scheduler (engine.ServingEngine) drives ONE compiled ragged
wide-step program over a slot-based KV-cache pool — admission,
interleaved prefill/decode, per-request sampling params, immediate
eviction — with every request's token stream bit-identical to its
solo run.  router.FabricRouter is the multi-pool front door: sticky
placement over N engine pools, fabric-wide backpressure, drain-and-
retire, and prefix-replay failover that extends the exactness
contract across pool death.  trace.make_poisson_trace generates the
seeded open-loop bench/test workloads.
"""

from .engine import ServingEngine, serve_one_at_a_time
from .pool import SlotPool
from .pool_worker import spawn_pool_worker
from .router import FabricRouter, ProcessPool, parse_pool_schedule
from .trace import Request, make_poisson_trace

__all__ = ["ServingEngine", "serve_one_at_a_time", "SlotPool",
           "FabricRouter", "ProcessPool", "parse_pool_schedule",
           "spawn_pool_worker", "Request", "make_poisson_trace"]
