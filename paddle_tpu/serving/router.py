"""Serving fabric: a sticky multi-pool router over N ServingEngines
(docs/SERVING.md §7) — the multi-pool front door ROADMAP item 3 names.

ONE FabricRouter owns the admission queue and places each incoming
Request onto exactly one pool (sticky slot placement): the placement
score orders LIVE pools by (occupancy, engine backlog, pid) and a
request only leaves its pool through failover.  Every pool is a whole
ServingEngine in its OWN fluid.Scope (the KV-cache persistable names
are fixed per model family, so pools sharing a scope would alias their
slot pools) with its own Executor; the router wraps every engine call
in scope_guard(pool.scope).  Pools advance in LOCKSTEP — one fabric
step steps every serving pool once — so the fabric clock, every
engine's `now`, and Request.arrival all share one virtual time axis.

Degradation contract (chaos-tested, tests/test_serving_fabric.py):

* Backpressure — the admission queue is the FABRIC-wide signal: an
  arrival that finds `queue_depth` requests already waiting (no pool
  could take them) is rejected loudly with a terminal
  REJECTED_QUEUE_FULL at the router.  Never a hang, never an unbounded
  queue.
* Drain-and-retire — pool removal stops new placements, lets in-flight
  requests finish, and only then retires the pool: no orphaned slots.
* Failover — a pool that misses `miss_beats` health beats (its step
  loop was killed: the `pool_kill` fault action) or whose step thread
  DIES (raises) is declared dead; its queued requests re-enter the
  router queue as-is and each in-flight request is RE-PLACED as a
  replay: prompt + the emitted-token prefix becomes the new prompt,
  the token budget shrinks by the prefix, and sample_step_base offsets
  the sampling keys past it — so the re-decoded stream continues the
  solo run's token sequence exactly (the PR 9 exactness contract
  extended across failover).  Survivors see only feed-value changes:
  zero retraces.

Control plane: stats() speaks the same verb shape launch.py's
_ScalingPolicy polls on pservers (queue depth / occupancy / rejection
and re-placement counters), control_service() wraps the router for
make_var_server so ONE supervisor scales trainers, pservers, and
serving pools from shared signals, and run(pool_schedule=...) is the
deterministic chaos/bench driver (`T:+N,T:-N` in fabric steps).
"""

import threading
import time

import numpy as np

__all__ = ["FabricRouter", "parse_pool_schedule"]


def parse_pool_schedule(spec):
    """'T:+N,T:-N' -> [(T, delta)] sorted by T.  T is in fabric STEPS
    for FabricRouter.run and in SECONDS for launch.py's supervisor loop
    — same grammar as --elastic-schedule / --pserver-schedule."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if part:
            t_s, _, d = part.partition(":")
            out.append((float(t_s), int(d)))
    out.sort(key=lambda e: e[0])
    return out


class _PoolHandle:
    """One serving pool: engine + its private scope + health state."""

    __slots__ = ("pid", "engine", "scope", "state", "killed",
                 "missed_beats", "compile_baseline")

    def __init__(self, pid, engine, scope):
        self.pid = int(pid)
        self.engine = engine
        self.scope = scope
        self.state = "live"  # live | draining | dead | retired
        self.killed = False  # SIGKILL-equivalent: step loop stops beating
        self.missed_beats = 0
        self.compile_baseline = None


class FabricRouter:
    """pool_factory() -> (engine, scope): builds a ServingEngine whose
    scope already holds the model weights.  Every pool must hold
    IDENTICAL weights (same startup seed / same checkpoint) — failover
    replays a prefix into a survivor and bit-exact continuation needs
    the same model on both sides."""

    def __init__(self, pool_factory, n_pools=1, queue_depth=None,
                 miss_beats=2, fault_schedule=None, max_pools=8):
        assert int(n_pools) >= 1, n_pools
        self.pool_factory = pool_factory
        self.queue_depth = None if queue_depth is None else int(queue_depth)
        assert self.queue_depth is None or self.queue_depth >= 0
        self.miss_beats = max(1, int(miss_beats))
        self.faults = fault_schedule
        self.max_pools = int(max_pools)
        self.pools = {}  # pid -> _PoolHandle (dead/retired pruned)
        self.queue = []  # router admission queue (arrival, rid) order
        self.now = 0
        self._next_pid = 0
        self._step_wall = []  # shared with every engine (latency base)
        self._results = {}
        self._prefix = {}  # rid -> emitted tokens carried over failovers
        self._pending_scale = []  # deltas from the control plane (RPC)
        self._lock = threading.RLock()
        self.counters = {"submitted": 0, "finished": 0, "rejected": 0,
                         "expired": 0, "replaced": 0, "pool_kills": 0,
                         "pools_added": 0, "pools_retired": 0,
                         "pools_died": 0}
        for _ in range(int(n_pools)):
            self.add_pool()

    # ---- pool membership -----------------------------------------------
    def add_pool(self):
        """Grow one pool: build it in its own scope, zero its caches,
        and fast-forward its clock onto the fabric's step axis (a pool
        joining at step T must admit arrivals <= T immediately)."""
        from ..core.scope import scope_guard

        with self._lock:
            if len(self._routable()) >= self.max_pools:
                raise RuntimeError(
                    "fabric at max_pools=%d" % self.max_pools)
            engine, scope = self.pool_factory()
            pid = self._next_pid
            self._next_pid += 1
            with scope_guard(scope):
                engine.exe.run(engine.cache_startup)
            engine.now = self.now
            engine._step_wall = self._step_wall  # one latency clock
            self.pools[pid] = _PoolHandle(pid, engine, scope)
            self.counters["pools_added"] += 1
            print("FABRIC POOL ADD pid=%d step=%d" % (pid, self.now),
                  flush=True)
            return pid

    def drain_pool(self, pid):
        """Begin drain-and-retire: no new placements; in-flight requests
        finish on their slots; the empty pool retires at a later step()."""
        with self._lock:
            h = self.pools[pid]
            if h.state == "live":
                h.state = "draining"
                print("FABRIC POOL DRAIN pid=%d step=%d"
                      % (pid, self.now), flush=True)

    def kill_pool(self, pid):
        """SIGKILL-equivalent: the pool's step loop stops responding
        (no beats, no steps).  Death is DECLARED by the health check
        after miss_beats missed beats — the failover path under test."""
        with self._lock:
            h = self.pools[pid]
            h.killed = True
            self.counters["pool_kills"] += 1
            print("FABRIC POOL KILL pid=%d step=%d" % (pid, self.now),
                  flush=True)

    def _routable(self):
        return [h for h in self.pools.values()
                if h.state in ("live", "draining")]

    def _live(self):
        return [h for h in self.pools.values() if h.state == "live"]

    def scale_pools(self, delta):
        """Apply a pool-count delta NOW (router thread): +N adds pools,
        -N drains the newest live pools (drain-and-retire, never a
        kill).  The control plane (RPC verb) uses request_scale instead
        so mutations stay on the stepping thread."""
        delta = int(delta)
        for _ in range(max(0, delta)):
            if len(self._routable()) < self.max_pools:
                self.add_pool()
        if delta < 0:
            victims = sorted(self._live(), key=lambda h: -h.pid)
            keep_min = 1  # never drain the last live pool
            n = min(-delta, max(0, len(self._live()) - keep_min))
            for h in victims[:n]:
                self.drain_pool(h.pid)

    def request_scale(self, delta):
        """Thread-safe scale request: queued and applied at the next
        fabric step boundary (the supervisor's RPC thread must not
        mutate pools mid-step)."""
        with self._lock:
            self._pending_scale.append(int(delta))

    # ---- request intake ------------------------------------------------
    def submit(self, req):
        with self._lock:
            live = {q.rid for q in self.queue}
            for h in self._routable():
                live.update(q.rid for q in h.engine.queue)
                live.update(s.req.rid
                            for _, s in h.engine.pool.active_slots())
            if req.rid in live:
                raise ValueError("duplicate request id %r" % (req.rid,))
            # capacity validation against any pool's geometry (all pools
            # share one config by construction)
            any_pool = next(iter(self.pools.values()))
            any_pool.engine.pool.validate(req)
            self.queue.append(req)
            self.queue.sort(key=lambda r: (r.arrival, r.rid))
            self.counters["submitted"] += 1

    # ---- terminal bookkeeping ------------------------------------------
    def _terminal(self, req, status):
        """Router-side terminal record, same shape as engine results."""
        self.counters["rejected" if status == "REJECTED_QUEUE_FULL"
                      else "expired"] += 1
        print("FABRIC %s rid=%r step=%d" % (status, req.rid, self.now),
              flush=True)
        wall = time.time()
        a = min(req.arrival_step, max(0, len(self._step_wall) - 1))
        self._results[req.rid] = {
            "tokens": np.asarray(self._prefix.get(req.rid, []), "int64"),
            "prompt_len": int(req.prompt.size),
            "arrival_step": req.arrival_step,
            "admit_step": None,
            "finish_step": self.now,
            "status": status,
            "latency_steps": self.now - req.arrival_step + 1,
            "latency_s": wall - (self._step_wall[a] if self._step_wall
                                 else wall),
        }

    def _harvest(self, h, rids):
        """Pull terminal results out of a pool's engine, stitching the
        failover prefix back onto replayed streams."""
        for rid in rids:
            r = dict(h.engine._results[rid])
            pref = self._prefix.pop(rid, None)
            if pref is not None:
                r["tokens"] = np.concatenate(
                    [np.asarray(pref, "int64"),
                     np.asarray(r["tokens"], "int64")])
                r["replayed"] = True
            if r["status"] == "OK":
                self.counters["finished"] += 1
            else:
                self.counters["rejected" if r["status"] ==
                              "REJECTED_QUEUE_FULL" else "expired"] += 1
            r["pool"] = h.pid
            self._results[rid] = r

    # ---- failover ------------------------------------------------------
    def _declare_dead(self, h):
        """Harvest a dead pool's queued AND in-flight requests and
        re-place them: queued ones re-enter the router queue verbatim;
        each in-flight one replays prompt + emitted prefix (original
        arrival kept, so its deadline budget and queue priority are
        unchanged)."""
        from .trace import Request

        h.state = "dead"
        self.counters["pools_died"] += 1
        n_q, n_f = len(h.engine.queue), len(h.engine.pool.active_slots())
        print("FABRIC POOL DEAD pid=%d step=%d requeue=%d replay=%d"
              % (h.pid, self.now, n_q, n_f), flush=True)
        for req in h.engine.queue:
            self.queue.append(req)
        for slot, s in h.engine.pool.active_slots():
            req = s.req
            prior = list(self._prefix.get(req.rid, []))
            prefix = prior + [int(t) for t in s.out]
            self._prefix[req.rid] = prefix
            emitted = len(s.out)
            replay = Request(
                rid=req.rid,
                prompt=np.concatenate(
                    [req.prompt, np.asarray(s.out, "int64")])
                if emitted else req.prompt,
                max_new_tokens=req.max_new_tokens - emitted,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed, eos_id=req.eos_id,
                arrival=req.arrival, deadline=req.deadline,
                sample_step_base=req.sample_step_base + emitted)
            self.queue.append(replay)
            self.counters["replaced"] += 1
            h.engine.pool.evict(slot)
        h.engine.queue = []
        self.queue.sort(key=lambda r: (r.arrival, r.rid))
        self.pools.pop(h.pid, None)

    # ---- placement -----------------------------------------------------
    def _score(self, h):
        """Placement score (lower is better): per-pool health is the
        gate (only live pools are scored at all), then occupancy, then
        the pool's own backlog, then pid for a stable tie-break."""
        active = len(h.engine.pool.active_slots())
        occ = active / float(h.engine.n_slots)
        return (occ, len(h.engine.queue), h.pid)

    def _place(self):
        """Route due arrivals onto pools; reject past the fabric-wide
        queue depth.  A routed request goes straight into its pool's
        engine queue against a KNOWN free slot, so pools never build
        private backlogs — the router's queue IS the fabric queue."""
        still, waiting = [], 0
        free = {h.pid: len(h.engine.pool.free_slots())
                for h in self._live()}
        for req in self.queue:
            if req.arrival > self.now:
                still.append(req)
                continue
            d = req.deadline
            if d is not None and self.now >= req.arrival_step + d:
                self._terminal(req, "DEADLINE_EXPIRED")
                continue
            target = None
            for h in sorted(self._live(), key=self._score):
                if free.get(h.pid, 0) > 0:
                    target = h
                    break
            if target is not None:
                free[target.pid] -= 1
                target.engine.submit(req)
            elif self.queue_depth is None or waiting < self.queue_depth:
                waiting += 1
                still.append(req)
            else:
                self._terminal(req, "REJECTED_QUEUE_FULL")
        self.queue = still

    # ---- one fabric step -----------------------------------------------
    def step(self):
        """Health beats -> failover -> placement -> lockstep pool steps
        -> drain retirement.  Returns the rids that reached a terminal
        state this fabric step."""
        from ..core.scope import scope_guard

        with self._lock:
            self._step_wall.append(time.time())
            for delta in self._pending_scale:
                self.scale_pools(delta)
            self._pending_scale = []
            self._maybe_inject_fault()
            terminal = []
            # health: a killed step loop stops beating; declare death
            # after miss_beats consecutive silent fabric steps
            for h in list(self._routable()):
                if h.killed:
                    h.missed_beats += 1
                    if h.missed_beats >= self.miss_beats:
                        self._declare_dead(h)
            self._place()
            for h in list(self._routable()):
                if h.killed:
                    continue
                try:
                    with scope_guard(h.scope):
                        done = h.engine.step()
                except Exception as e:  # dead step thread: fail over NOW
                    print("FABRIC POOL STEP DIED pid=%d step=%d: %r"
                          % (h.pid, self.now, e), flush=True)
                    self._declare_dead(h)
                    continue
                h.missed_beats = 0
                if done:
                    self._harvest(h, done)
                    terminal.extend(done)
                if (h.state == "draining" and not h.engine.queue
                        and not h.engine.pool.active_slots()):
                    h.state = "retired"
                    self.counters["pools_retired"] += 1
                    print("FABRIC POOL RETIRED pid=%d step=%d"
                          % (h.pid, self.now), flush=True)
                    self.pools.pop(h.pid, None)
            self.now += 1
            return terminal

    def _maybe_inject_fault(self):
        """One fault-schedule slot per fabric step ('fabric' direction):
        a pool_kill action kills one live pool — an explicit
        'pool_kill:<pid>' names the victim, a bare 'pool_kill' picks one
        deterministically from the schedule's seeded per-frame hash."""
        if self.faults is None:
            return
        idx, action = self.faults.next_action("fabric")
        base, _, arg = str(action).partition(":")
        if base != "pool_kill":
            return
        live = sorted(self._live(), key=lambda h: h.pid)
        if not live:
            return
        if arg:
            pid = int(arg)
            if pid not in self.pools:
                return
        else:
            pick = int(self.faults.delay_fraction(idx) * len(live))
            pid = live[pick % len(live)].pid
        self.kill_pool(pid)

    # ---- control plane -------------------------------------------------
    def stats(self):
        """The supervisor's shared signal set — same verb shape the
        PR 15 pserver scaler polls: fabric queue depth, mean live-pool
        occupancy, cumulative rejection / re-placement counters (the
        poller diffs them), and per-pool detail."""
        with self._lock:
            live = self._live()
            occ = (sum(len(h.engine.pool.active_slots())
                       / float(h.engine.n_slots) for h in live)
                   / len(live)) if live else 0.0
            sub = max(1, self.counters["submitted"])
            per_pool = {
                str(h.pid): {
                    "state": h.state,
                    "active_slots": len(h.engine.pool.active_slots()),
                    "n_slots": h.engine.n_slots,
                    "backlog": len(h.engine.queue),
                    "compile_count": h.engine.exe.compile_count,
                    # run-MEAN slot occupancy (the engine accumulates
                    # per step) — the instantaneous active_slots reads
                    # 0 at any quiesced boundary
                    "mean_occupancy": round(
                        h.engine.counters["occupancy_sum"]
                        / max(1, h.engine.counters["steps"]), 4),
                }
                for h in self.pools.values()}
            s = dict(self.counters)
            s.update({
                "n_pools": len(live),
                "queue_depth": len([q for q in self.queue
                                    if q.arrival <= self.now]),
                "occupancy": round(occ, 4),
                "rejection_rate": round(
                    self.counters["rejected"] / float(sub), 4),
                "step": self.now,
                "pools": per_pool,
            })
            return s

    def control_service(self):
        """A make_var_server-compatible service: the router side of the
        unified control plane.  Verbs: stats, scale_pools(delta),
        drain_pool(pid), kill_pool(pid) — scale/drain/kill mutate via
        request_scale/flags so the stepping thread applies them at a
        step boundary."""
        router = self

        class _Control:
            def handle(self, verb, **kw):
                # errors ship to the client as {"__error__": ...} (the
                # pserver convention): raising here would only drop the
                # connection and surface as a retry timeout
                try:
                    if verb == "stats":
                        return router.stats()
                    if verb == "scale_pools":
                        router.request_scale(int(kw.get("delta", 0)))
                        return {"ok": True,
                                "n_pools": len(router._live())}
                    if verb == "drain_pool":
                        with router._lock:
                            router.drain_pool(int(kw["pid"]))
                        return {"ok": True}
                    if verb == "kill_pool":
                        with router._lock:
                            router.kill_pool(int(kw["pid"]))
                        return {"ok": True}
                    raise ValueError(
                        "unknown fabric verb %r" % (verb,))
                except Exception as e:
                    return {"__error__": "%s" % (e,)}

        return _Control()

    def serve_control(self, endpoint="127.0.0.1:0"):
        """Expose the control plane over RPC (threaded VarServer): the
        remote half of launch.py's --serve-router supervision."""
        from ..distributed.rpc import make_var_server

        srv = make_var_server(endpoint, self.control_service())
        srv.start()
        return srv

    # ---- episode driver ------------------------------------------------
    def run(self, requests=None, max_steps=100000, pool_schedule=None):
        """Serve `requests` to completion (plus anything queued).
        `pool_schedule` = [(fabric_step, delta)] or a 'T:+N,T:-N'
        string — the deterministic chaos/bench driver riding the exact
        scale_pools machinery the supervisor uses.  Returns (results,
        stats)."""
        if isinstance(pool_schedule, str):
            pool_schedule = parse_pool_schedule(pool_schedule)
        sched = sorted(pool_schedule or [], key=lambda e: e[0])
        for r in requests or []:
            self.submit(r)
        t0 = time.time()
        while True:
            with self._lock:
                busy = bool(self.queue) or any(
                    h.engine.queue or h.engine.pool.active_slots()
                    for h in self._routable())
                pending = bool(sched) or bool(self._pending_scale)
            if not busy and not pending:
                break
            while sched and sched[0][0] <= self.now:
                self.scale_pools(sched.pop(0)[1])
            self.step()
            if self.now >= max_steps:
                raise RuntimeError(
                    "fabric exceeded max_steps=%d with work pending"
                    % max_steps)
        wall = time.time() - t0
        stats = self.stats()
        stats["wall_s"] = round(wall, 4)
        new_tokens = sum(
            int(np.asarray(r["tokens"]).size)
            for r in self._results.values() if r["status"] == "OK")
        stats["new_tokens"] = new_tokens
        stats["tokens_per_s"] = (round(new_tokens / wall, 1)
                                 if wall else 0.0)
        return dict(self._results), stats
