"""Serving fabric: a sticky multi-pool router over N ServingEngines
(docs/SERVING.md §7) — the multi-pool front door ROADMAP item 3 names.

ONE FabricRouter owns the admission queue and places each incoming
Request onto exactly one pool (sticky slot placement): the placement
score orders LIVE pools by (occupancy, engine backlog, pid) and a
request only leaves its pool through failover.  Every pool is a whole
ServingEngine in its OWN fluid.Scope (the KV-cache persistable names
are fixed per model family, so pools sharing a scope would alias their
slot pools) with its own Executor; the router wraps every engine call
in scope_guard(pool.scope).  Pools advance in LOCKSTEP — one fabric
step steps every serving pool once — so the fabric clock, every
engine's `now`, and Request.arrival all share one virtual time axis.

Degradation contract (chaos-tested, tests/test_serving_fabric.py):

* Backpressure — the admission queue is the FABRIC-wide signal: an
  arrival that finds `queue_depth` requests already waiting (no pool
  could take them) is rejected loudly with a terminal
  REJECTED_QUEUE_FULL at the router.  Never a hang, never an unbounded
  queue.
* Drain-and-retire — pool removal stops new placements, lets in-flight
  requests finish, and only then retires the pool: no orphaned slots.
* Failover — a pool that misses `miss_beats` health beats (its step
  loop was killed: the `pool_kill` fault action) or whose step thread
  DIES (raises) is declared dead; its queued requests re-enter the
  router queue as-is and each in-flight request is RE-PLACED as a
  replay: prompt + the emitted-token prefix becomes the new prompt,
  the token budget shrinks by the prefix, and sample_step_base offsets
  the sampling keys past it — so the re-decoded stream continues the
  solo run's token sequence exactly (the PR 9 exactness contract
  extended across failover).  Survivors see only feed-value changes:
  zero retraces.

Process pools (pool_mode="process", docs/SERVING.md §7): each pool is
a REAL worker process (serving/pool_worker.py) hosting one engine
behind a VarServer, driven over RPC through the ProcessPool backend —
per-verb deadlines with bounded exponential backoff (rpc.CallPolicy),
an unacked-submit resend queue the worker dedups, and a router-side
mirror of slots/queue rebuilt from each step reply.  A worker death
surfaces as a BOUNDED RPC failure inside step (or as a supervisor
death report via report_worker_death) — never a hang — and flows into
the exact same _declare_dead replay path, so failover stays
token-exact across a real SIGKILL.  Cross-pool placement lets pools
of different sizes coexist: a request is placed only on pools whose
t_max fits len(prompt)+max_new (best-fit), and one that fits NO pool
is rejected loudly (submit raises; a fit that died mid-queue yields
terminal REJECTED_NO_FIT), never silently truncated.

Control plane: stats() speaks the same verb shape launch.py's
_ScalingPolicy polls on pservers (queue depth / occupancy / rejection
and re-placement counters), control_service() wraps the router for
make_var_server so ONE supervisor scales trainers, pservers, and
serving pools from shared signals, and run(pool_schedule=...) is the
deterministic chaos/bench driver (`T:+N,T:-N` in fabric steps).
"""

import threading
import time

import numpy as np

__all__ = ["FabricRouter", "ProcessPool", "parse_pool_schedule"]


def parse_pool_schedule(spec):
    """'T:+N,T:-N' -> [(T, delta)] sorted by T.  T is in fabric STEPS
    for FabricRouter.run and in SECONDS for launch.py's supervisor loop
    — same grammar as --elastic-schedule / --pserver-schedule."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if part:
            t_s, _, d = part.partition(":")
            out.append((float(t_s), int(d)))
    out.sort(key=lambda e: e[0])
    return out


class _PoolHandle:
    """One serving pool: engine + its private scope + health state."""

    __slots__ = ("pid", "engine", "scope", "state", "killed",
                 "missed_beats", "compile_baseline")

    def __init__(self, pid, engine, scope):
        self.pid = int(pid)
        self.engine = engine
        self.scope = scope
        self.state = "live"  # live | draining | dead | retired
        self.killed = False  # SIGKILL-equivalent: step loop stops beating
        self.missed_beats = 0
        self.compile_baseline = None


# ---------------------------------------------------------------------------
# process-pool backend (pool_mode="process"): the pool is a REAL worker
# process (serving/pool_worker.py) driven over RPC — same interface the
# router speaks to an in-process ServingEngine, mirrored from the
# worker's step replies.
# ---------------------------------------------------------------------------
class _WireSlot:
    """Router-side mirror of one active worker slot: the original
    Request plus the emitted-token prefix from the worker's LAST step
    reply.  At worker death this is the replay source — `out` may lag
    the worker by the lost reply, which only costs re-decode work (the
    keyed sampler re-draws the identical tokens), never exactness."""

    __slots__ = ("req", "out")

    def __init__(self, req, out=()):
        self.req = req
        self.out = list(out)


class _MirrorPool:
    """Duck-types the SlotPool surface the router reads (active_slots /
    free_slots / evict / validate / fits), rebuilt from each step
    reply.  Slots are keyed by rid — the router never addresses a
    remote cache row directly."""

    def __init__(self, n_slots, width, t_max):
        self.n_slots = int(n_slots)
        self.width = int(width)
        self.t_max = int(t_max)
        self.slots = {}  # rid -> _WireSlot (insertion-ordered)
        self._free = self.n_slots

    # capacity rules are THE SlotPool's, verbatim (they only key off
    # t_max): one source of truth on both sides of the process boundary
    from .pool import SlotPool as _SP
    fits = _SP.fits
    validate = _SP.validate
    del _SP

    def active_slots(self):
        return list(self.slots.items())

    def free_slots(self):
        return list(range(self._free))

    def evict(self, rid):
        return self.slots.pop(rid, None)

    def set_state(self, slots, free, reqs):
        self.slots = {e["rid"]: _WireSlot(reqs[e["rid"]], e["out"])
                      for e in slots if e["rid"] in reqs}
        self._free = int(free)


class _ExeStats:
    """Stand-in for the engine's Executor in router stats: the worker
    reports its compile_count each step (the zero-retrace failover bar
    applies to process pools unchanged)."""

    __slots__ = ("compile_count",)

    def __init__(self, compile_count=0):
        self.compile_count = int(compile_count)


class ProcessPool:
    """One out-of-process pool: duck-types the ServingEngine surface
    FabricRouter drives (queue / pool / submit / step / _results /
    counters / exe.compile_count) over RPCClient with per-verb
    deadlines + bounded exponential backoff (rpc.CallPolicy) and an
    UNACKED-SUBMIT RESEND QUEUE — a submit whose ack was lost resends
    next step and the worker answers dup instead of double-admitting.
    A worker death surfaces as an RPC failure inside submit-flush or
    step (bounded by the policy deadline, never a hang); the router's
    existing dead-step-thread path then declares the pool dead and
    replays its mirror."""

    def __init__(self, endpoint, proc=None, policy=None):
        from ..distributed.rpc import CallPolicy, RPCClient

        self.endpoint = str(endpoint)
        self.proc = proc  # subprocess handle when the router spawned it
        self.policy = policy or CallPolicy(
            timeout_s=5.0, deadline_s=15.0, attempts=3,
            verb_deadlines={"submit": 5.0, "shutdown": 2.0})
        # private client (not the shared .get cache): a retired worker's
        # endpoint must not leave a poisoned cached connection behind
        self._cli = RPCClient(self.endpoint, timeout=self.policy.timeout_s,
                              retries=2, retry_wait=0.05)
        hello = self.policy.call(self._cli, "stats")
        self.n_slots = int(hello["n_slots"])
        self.worker_pid = int(hello.get("pid", 0))
        self.pool = _MirrorPool(hello["n_slots"], hello["width"],
                                hello["t_max"])
        # fast-path config mirrored from the worker: the router's
        # prefix-aware placement needs the pool's match granularity
        # (0 = pool has no prefix cache / no draft)
        self.prefix_rows = int(hello.get("prefix_rows", 0))
        self.prefix_chunk = int(hello.get("prefix_chunk", 0))
        self.spec_k = int(hello.get("spec_k", 0))
        self.queue = []        # mirror: submitted, not yet admitted
        self._reqs = {}        # rid -> Request, until terminal
        self._unacked = []     # submits with no ack yet (resend queue)
        self._ack = []         # harvested rids to ack on the next step
        self._results = {}
        self.now = int(hello.get("now", 0))
        self._step_wall = []   # assigned by the router (shared clock)
        self.counters = {
            "occupancy_sum": float(hello.get("occupancy_sum", 0.0)),
            "steps": int(hello.get("steps", 0)),
            "spec_proposed": int(hello.get("spec_proposed", 0)),
            "spec_accepted": int(hello.get("spec_accepted", 0)),
            "prefix_hits": int(hello.get("prefix_hits", 0)),
            "prefix_misses": int(hello.get("prefix_misses", 0)),
            "prefix_tokens_reused": int(
                hello.get("prefix_tokens_reused", 0))}
        self.exe = _ExeStats(hello.get("compile_count", 0))

    # ---- the engine surface the router drives --------------------------
    def submit(self, req):
        self.pool.validate(req)
        self._reqs[req.rid] = req
        self.queue.append(req)
        self._unacked.append(req)
        self._flush_unacked(raise_on_fail=False)

    def _flush_unacked(self, raise_on_fail):
        pending = list(self._unacked)
        still = []
        while pending:
            req = pending.pop(0)
            try:
                r = self.policy.call(self._cli, "submit",
                                     req=req.to_wire())
            except ConnectionError:
                if raise_on_fail:
                    # keep everything unsent for the failover requeue
                    self._unacked = still + [req] + pending
                    raise
                still.append(req)
                continue
            if not r.get("ok"):
                raise RuntimeError(
                    "pool worker %s refused submit rid=%r: %r"
                    % (self.endpoint, req.rid, r))
        self._unacked = still

    def step(self):
        """Flush pending submits, then ONE remote engine step at the
        fabric clock.  Raises (bounded by the policy deadline) on a
        dead worker — the router's failover path catches it."""
        self._flush_unacked(raise_on_fail=True)
        rep = self.policy.call(self._cli, "step", now=int(self.now),
                               ack=list(self._ack))
        self._ack = []
        return self._apply_reply(rep)

    def _apply_reply(self, rep):
        self.now = int(rep["now"])
        self.counters["occupancy_sum"] = float(rep["occupancy_sum"])
        self.counters["steps"] = int(rep["steps"])
        for k in ("spec_proposed", "spec_accepted", "prefix_hits",
                  "prefix_misses", "prefix_tokens_reused"):
            self.counters[k] = int(rep.get(k, self.counters.get(k, 0)))
        self.exe.compile_count = int(rep["compile_count"])
        done = []
        for r in rep["results"]:
            r = dict(r)
            rid = r.pop("rid")
            self._results[rid] = r
            self._reqs.pop(rid, None)
            self._ack.append(rid)
            done.append(rid)
        self.pool.set_state(rep["slots"], rep["free"], self._reqs)
        worker_q = [self._reqs[rid] for rid in rep["queued"]
                    if rid in self._reqs]
        self.queue = sorted(
            worker_q + [q for q in self._unacked if q.rid in self._reqs],
            key=lambda r: (r.arrival, str(r.rid)))
        return done

    def register_prefix(self, tokens):
        """Register a common prompt prefix in the worker's prefix cache
        (engine.register_prefix over the wire).  Returns the prefix row
        id, or None when the worker has no prefix cache / the tokens
        are shorter than one chunk."""
        rep = self.policy.call(
            self._cli, "register_prefix",
            tokens=np.asarray(tokens, "int64").reshape(-1))
        row = rep.get("row") if isinstance(rep, dict) else None
        return None if row is None else int(row)

    # ---- lifecycle -----------------------------------------------------
    def proc_kill(self):
        """SIGKILL the live worker (the `pool_proc_kill` fault action).
        Detection stays with the RPC path: the NEXT step's failure is
        what declares the pool dead — exactly a real crash."""
        import signal

        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            return True
        if self.worker_pid:
            try:
                import os

                os.kill(self.worker_pid, signal.SIGKILL)
                return True
            except (OSError, ProcessLookupError):
                pass
        return False

    def close(self, kill=False):
        """Retire the worker: graceful shutdown verb (drain-and-retire)
        or SIGKILL (failover cleanup — a hung-but-alive worker must not
        keep decoding an already-replayed stream).  Never leaves an
        orphan process behind."""
        if not kill:
            try:
                self.policy.call(self._cli, "shutdown")
            except (ConnectionError, RuntimeError):
                kill = True
        if self.proc is not None:
            try:
                if kill:
                    self.proc.kill()
                self.proc.wait(timeout=10)
            except Exception:
                try:
                    self.proc.kill()
                    self.proc.wait(timeout=10)
                except Exception:
                    pass
        elif kill:
            self.proc_kill()
        try:
            self._cli.close()
        except Exception:
            pass


class FabricRouter:
    """pool_factory() -> (engine, scope): builds a ServingEngine whose
    scope already holds the model weights.  Every pool must hold
    IDENTICAL weights (same startup seed / same checkpoint) — failover
    replays a prefix into a survivor and bit-exact continuation needs
    the same model on both sides."""

    def __init__(self, pool_factory, n_pools=1, queue_depth=None,
                 miss_beats=2, fault_schedule=None, max_pools=8,
                 pool_mode="inproc", rpc_policy=None):
        # process mode may start empty and attach workers over the
        # control plane (launch.py's supervised children)
        assert int(n_pools) >= (0 if pool_mode == "process" else 1), \
            n_pools
        assert pool_mode in ("inproc", "process"), pool_mode
        self.pool_mode = pool_mode
        self.rpc_policy = rpc_policy
        self.pool_factory = pool_factory
        self.queue_depth = None if queue_depth is None else int(queue_depth)
        assert self.queue_depth is None or self.queue_depth >= 0
        self.miss_beats = max(1, int(miss_beats))
        self.faults = fault_schedule
        self.max_pools = int(max_pools)
        self.pools = {}  # pid -> _PoolHandle (dead/retired pruned)
        self.queue = []  # router admission queue (arrival, rid) order
        self.now = 0
        self._next_pid = 0
        self._step_wall = []  # shared with every engine (latency base)
        self._results = {}
        self._prefix = {}  # rid -> emitted tokens carried over failovers
        # fabric-wide prefix-cache registry: the token arrays registered
        # via register_prefix, kept so (a) placement can estimate a
        # PROCESS pool's match length without an RPC and (b) pools that
        # join later (scale-up, failover respawn) get every registered
        # prefix replayed into their cache
        self._prefixes = []
        self._pending_scale = []  # deltas from the control plane (RPC)
        self._lock = threading.RLock()
        self.counters = {"submitted": 0, "finished": 0, "rejected": 0,
                         "expired": 0, "replaced": 0, "pool_kills": 0,
                         "pools_added": 0, "pools_retired": 0,
                         "pools_died": 0}
        for _ in range(int(n_pools)):
            self.add_pool()

    # ---- pool membership -----------------------------------------------
    def add_pool(self):
        """Grow one pool.  In-process: build it in its own scope, zero
        its caches.  Process mode: the factory returns (endpoint, proc)
        — or a bare endpoint — of a READY pool worker and the router
        wraps it in a ProcessPool backend.  Either way the pool's clock
        fast-forwards onto the fabric's step axis (a pool joining at
        step T must admit arrivals <= T immediately)."""
        from ..core.scope import scope_guard

        with self._lock:
            if len(self._routable()) >= self.max_pools:
                raise RuntimeError(
                    "fabric at max_pools=%d" % self.max_pools)
            if self.pool_mode == "process":
                made = self.pool_factory()
                endpoint, proc = (made if isinstance(made, tuple)
                                  else (made, None))
                return self.attach_worker(endpoint, proc=proc)
            engine, scope = self.pool_factory()
            with scope_guard(scope):
                engine.exe.run(engine.cache_startup)
            return self._register_pool(engine, scope)

    def attach_worker(self, endpoint, proc=None):
        """Adopt one ALREADY-RUNNING pool worker process (the
        supervisor's spawn/respawn path attaches its children here over
        the control plane; the worker ran its own cache startup)."""
        with self._lock:
            if len(self._routable()) >= self.max_pools:
                raise RuntimeError(
                    "fabric at max_pools=%d" % self.max_pools)
            engine = ProcessPool(endpoint, proc=proc,
                                 policy=self.rpc_policy)
            return self._register_pool(engine, None)

    def _register_pool(self, engine, scope):
        pid = self._next_pid
        self._next_pid += 1
        engine.now = self.now
        engine._step_wall = self._step_wall  # one latency clock
        self.pools[pid] = _PoolHandle(pid, engine, scope)
        self.counters["pools_added"] += 1
        print("FABRIC POOL ADD pid=%d step=%d%s"
              % (pid, self.now,
                 " worker=%s" % engine.endpoint
                 if scope is None else ""), flush=True)
        # replay every fabric-registered prefix into the new pool's
        # cache: a pool joining after registration (scale-up, failover
        # respawn) must serve prefix-hit traffic identically to the
        # pools that were present at registration time
        for toks in self._prefixes:
            self._register_prefix_on(self.pools[pid], toks)
        return pid

    # ---- prefix-cache registration -------------------------------------
    def _register_prefix_on(self, h, tokens):
        """Register `tokens` on one pool (skipped when the pool carries
        no prefix cache).  Returns the pool's prefix row id or None."""
        from contextlib import nullcontext

        from ..core.scope import scope_guard

        eng = h.engine
        if getattr(eng, "register_prefix", None) is None:
            return None
        if (getattr(eng, "prefix", None) is None
                and not getattr(eng, "prefix_rows", 0)):
            return None
        with (scope_guard(h.scope) if h.scope is not None
              else nullcontext()):
            return eng.register_prefix(tokens)

    def register_prefix(self, tokens):
        """Register one common prompt prefix FABRIC-wide: every
        routable pool with a prefix cache prefills and stores it, and
        the router records the tokens so placement can estimate match
        lengths for process pools and so late-joining pools get the
        prefix replayed (see _register_pool).  Pools without a prefix
        cache are skipped — a mixed fabric degrades to cold prefill on
        them, never to a wrong stream.  Call while the fabric is idle
        (engines refuse registration with slots busy).  Returns
        {pid: row} for the pools that took it."""
        tokens = np.asarray(tokens, "int64").reshape(-1)
        with self._lock:
            rows = {}
            for h in self._routable():
                row = self._register_prefix_on(h, tokens)
                if row is not None:
                    rows[h.pid] = row
            self._prefixes.append(tokens.copy())
            return rows

    def _prefix_match_len(self, h, req):
        """Expected prefix-cache reuse for `req` on pool `h` in tokens
        (0 = no prefix cache or no match).  In-process pools answer
        from the engine's own host index (exact, counter-free —
        match() doesn't bump hit/miss); process pools are estimated
        from the router's registry floored to the worker's chunk, which
        matches the worker's own admission-time match for every prefix
        registered THROUGH the router."""
        eng = h.engine
        pfx = getattr(eng, "prefix", None)
        if pfx is not None:
            return int(pfx.match(req.prompt)[1])
        chunk = int(getattr(eng, "prefix_chunk", 0) or 0)
        if chunk <= 0 or not self._prefixes:
            return 0
        best = 0
        p = req.prompt
        for toks in self._prefixes:
            n = min(int(toks.size), int(p.size) - 1)
            if n < chunk:
                continue
            eq = p[:n] == toks[:n]
            lcp = n if eq.all() else int(np.argmax(~eq))
            best = max(best, (lcp // chunk) * chunk)
        return best

    def drain_pool(self, pid):
        """Begin drain-and-retire: no new placements; in-flight requests
        finish on their slots; the empty pool retires at a later step()."""
        with self._lock:
            h = self.pools[pid]
            if h.state == "live":
                h.state = "draining"
                print("FABRIC POOL DRAIN pid=%d step=%d"
                      % (pid, self.now), flush=True)

    def kill_pool(self, pid):
        """SIGKILL-equivalent: the pool's step loop stops responding
        (no beats, no steps).  Death is DECLARED by the health check
        after miss_beats missed beats — the failover path under test."""
        with self._lock:
            h = self.pools[pid]
            h.killed = True
            self.counters["pool_kills"] += 1
            print("FABRIC POOL KILL pid=%d step=%d" % (pid, self.now),
                  flush=True)

    def proc_kill_pool(self, pid):
        """REAL SIGKILL of a process pool's worker (the
        `pool_proc_kill` fault action).  Unlike kill_pool the handle is
        NOT flagged: detection must ride the RPC failure at the next
        step — exactly how an unscheduled crash presents.  In-process
        pools fall back to the cooperative kill."""
        with self._lock:
            h = self.pools[pid]
            if getattr(h.engine, "proc_kill", None) is None:
                print("FABRIC POOL PROC-KILL pid=%d step=%d: in-process "
                      "pool, falling back to cooperative kill"
                      % (pid, self.now), flush=True)
                return self.kill_pool(pid)
            h.engine.proc_kill()
            self.counters["pool_kills"] += 1
            print("FABRIC POOL PROC-KILL pid=%d step=%d worker_pid=%d"
                  % (pid, self.now, h.engine.worker_pid), flush=True)

    def report_worker_death(self, pid=None, endpoint=None):
        """Supervisor death report (launch.py's on_child_death hook):
        the named pool is declared dead at the NEXT step without
        spending the RPC policy deadline discovering it."""
        with self._lock:
            for h in list(self.pools.values()):
                if (h.pid == pid
                        or (endpoint is not None
                            and getattr(h.engine, "endpoint", None)
                            == endpoint)):
                    h.killed = True
                    h.missed_beats = self.miss_beats
                    print("FABRIC POOL DEATH-REPORTED pid=%d step=%d"
                          % (h.pid, self.now), flush=True)
                    return True
            return False

    def _routable(self):
        return [h for h in self.pools.values()
                if h.state in ("live", "draining")]

    def _live(self):
        return [h for h in self.pools.values() if h.state == "live"]

    def scale_pools(self, delta):
        """Apply a pool-count delta NOW (router thread): +N adds pools,
        -N drains the newest live pools (drain-and-retire, never a
        kill).  The control plane (RPC verb) uses request_scale instead
        so mutations stay on the stepping thread."""
        delta = int(delta)
        for _ in range(max(0, delta)):
            if len(self._routable()) < self.max_pools:
                self.add_pool()
        if delta < 0:
            victims = sorted(self._live(), key=lambda h: -h.pid)
            keep_min = 1  # never drain the last live pool
            n = min(-delta, max(0, len(self._live()) - keep_min))
            for h in victims[:n]:
                self.drain_pool(h.pid)

    def request_scale(self, delta):
        """Thread-safe scale request: queued and applied at the next
        fabric step boundary (the supervisor's RPC thread must not
        mutate pools mid-step)."""
        with self._lock:
            self._pending_scale.append(int(delta))

    # ---- request intake ------------------------------------------------
    def submit(self, req):
        with self._lock:
            live = {q.rid for q in self.queue}
            for h in self._routable():
                live.update(q.rid for q in h.engine.queue)
                live.update(s.req.rid
                            for _, s in h.engine.pool.active_slots())
            if req.rid in live:
                raise ValueError("duplicate request id %r" % (req.rid,))
            # cross-pool capacity: pools of DIFFERENT slot/width/t_max
            # sizes coexist — the request must fit SOME routable pool
            # (placement then keys long-context requests to big pools).
            # Reject-with-reason, never silently truncate.
            routable = self._routable()
            if not any(h.engine.pool.fits(req) for h in routable):
                cap = max((h.engine.pool.t_max for h in routable),
                          default=0)
                raise ValueError(
                    "request %r exceeds every pool's capacity: prompt "
                    "%d + new %d > largest t_max %d + 1 — no pool fits"
                    % (req.rid, req.prompt.size, req.max_new_tokens,
                       cap))
            self.queue.append(req)
            self.queue.sort(key=lambda r: (r.arrival, r.rid))
            self.counters["submitted"] += 1

    # ---- terminal bookkeeping ------------------------------------------
    def _terminal(self, req, status):
        """Router-side terminal record, same shape as engine results."""
        self.counters["rejected" if status.startswith("REJECTED")
                      else "expired"] += 1
        print("FABRIC %s rid=%r step=%d" % (status, req.rid, self.now),
              flush=True)
        wall = time.time()
        a = min(req.arrival_step, max(0, len(self._step_wall) - 1))
        self._results[req.rid] = {
            "tokens": np.asarray(self._prefix.get(req.rid, []), "int64"),
            "prompt_len": int(req.prompt.size),
            "arrival_step": req.arrival_step,
            "admit_step": None,
            "finish_step": self.now,
            "status": status,
            "latency_steps": self.now - req.arrival_step + 1,
            "latency_s": wall - (self._step_wall[a] if self._step_wall
                                 else wall),
        }

    def _harvest(self, h, rids):
        """Pull terminal results out of a pool's engine, stitching the
        failover prefix back onto replayed streams."""
        for rid in rids:
            r = dict(h.engine._results[rid])
            pref = self._prefix.pop(rid, None)
            if pref is not None:
                r["tokens"] = np.concatenate(
                    [np.asarray(pref, "int64"),
                     np.asarray(r["tokens"], "int64")])
                r["replayed"] = True
            if r["status"] == "OK":
                self.counters["finished"] += 1
            else:
                self.counters["rejected" if r["status"].startswith(
                    "REJECTED") else "expired"] += 1
            r["pool"] = h.pid
            self._results[rid] = r

    # ---- failover ------------------------------------------------------
    def _declare_dead(self, h):
        """Harvest a dead pool's queued AND in-flight requests and
        re-place them: queued ones re-enter the router queue verbatim;
        each in-flight one replays prompt + emitted prefix (original
        arrival kept, so its deadline budget and queue priority are
        unchanged)."""
        from .trace import Request

        h.state = "dead"
        self.counters["pools_died"] += 1
        n_q, n_f = len(h.engine.queue), len(h.engine.pool.active_slots())
        print("FABRIC POOL DEAD pid=%d step=%d requeue=%d replay=%d"
              % (h.pid, self.now, n_q, n_f), flush=True)
        for req in h.engine.queue:
            self.queue.append(req)
        for slot, s in h.engine.pool.active_slots():
            req = s.req
            prior = list(self._prefix.get(req.rid, []))
            prefix = prior + [int(t) for t in s.out]
            self._prefix[req.rid] = prefix
            emitted = len(s.out)
            replay = Request(
                rid=req.rid,
                prompt=np.concatenate(
                    [req.prompt, np.asarray(s.out, "int64")])
                if emitted else req.prompt,
                max_new_tokens=req.max_new_tokens - emitted,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed, eos_id=req.eos_id,
                arrival=req.arrival, deadline=req.deadline,
                sample_step_base=req.sample_step_base + emitted)
            self.queue.append(replay)
            self.counters["replaced"] += 1
            h.engine.pool.evict(slot)
        h.engine.queue = []
        self.queue.sort(key=lambda r: (r.arrival, r.rid))
        self.pools.pop(h.pid, None)
        if isinstance(h.engine, ProcessPool):
            # reap the dead (or hung-but-alive) worker: its streams are
            # being replayed elsewhere, and orphans are a test failure
            h.engine.close(kill=True)

    # ---- placement -----------------------------------------------------
    def _score(self, h, req):
        """Placement score (lower is better): per-pool health is the
        gate (only live pools are scored at all), then occupancy, then
        the pool's own backlog, then the request's REMAINING WORK on
        this pool — (prompt - prefix match) + max_new.  The raw PR 18
        best-fit key len(prompt)+max_new OVERESTIMATES footprint for
        prefix-hit requests: a long-template request whose prefix is
        resident would spill to the big pools even though most of its
        prompt never prefills.  Scoring remaining work keeps
        long-template traffic on the pools holding its prefix; on a
        fabric with no prefix caches the term is pool-independent and
        the ordering falls through to CAPACITY (best-fit: among fitting
        pools a short request prefers the smallest, keeping big pools
        free for the long-context requests only they can hold) then pid
        for a stable tie-break — the pre-prefix ordering, unchanged."""
        active = len(h.engine.pool.active_slots())
        occ = active / float(h.engine.n_slots)
        est_work = (int(req.prompt.size) - self._prefix_match_len(h, req)
                    + int(req.max_new_tokens))
        return (occ, len(h.engine.queue), est_work,
                h.engine.pool.t_max, h.pid)

    def _place(self):
        """Route due arrivals onto pools; reject past the fabric-wide
        queue depth.  A routed request goes straight into its pool's
        engine queue against a KNOWN free slot, so pools never build
        private backlogs — the router's queue IS the fabric queue.
        Cross-pool placement keys off len(prompt)+max_new vs each
        pool's t_max: a request no LIVE pool can hold (the big pool
        died or drained since submit) terminates loudly with
        REJECTED_NO_FIT — reject-with-reason, never a silent truncate
        and never an unbounded wait."""
        still, waiting = [], 0
        free = {h.pid: len(h.engine.pool.free_slots())
                for h in self._live()}
        for req in self.queue:
            if req.arrival > self.now:
                still.append(req)
                continue
            d = req.deadline
            if d is not None and self.now >= req.arrival_step + d:
                self._terminal(req, "DEADLINE_EXPIRED")
                continue
            fitting = [h for h in self._live()
                       if h.engine.pool.fits(req)]
            if not fitting:
                self._terminal(req, "REJECTED_NO_FIT")
                continue
            target = None
            for h in sorted(fitting,
                            key=lambda h: self._score(h, req)):
                if free.get(h.pid, 0) > 0:
                    target = h
                    break
            if target is not None:
                free[target.pid] -= 1
                target.engine.submit(req)
            elif self.queue_depth is None or waiting < self.queue_depth:
                waiting += 1
                still.append(req)
            else:
                self._terminal(req, "REJECTED_QUEUE_FULL")
        self.queue = still

    # ---- one fabric step -----------------------------------------------
    def step(self):
        """Health beats -> failover -> placement -> lockstep pool steps
        -> drain retirement.  Returns the rids that reached a terminal
        state this fabric step."""
        from contextlib import nullcontext

        from ..core.scope import scope_guard

        with self._lock:
            self._step_wall.append(time.time())
            for delta in self._pending_scale:
                self.scale_pools(delta)
            self._pending_scale = []
            self._maybe_inject_fault()
            terminal = []
            # health: a killed step loop stops beating; declare death
            # after miss_beats consecutive silent fabric steps
            for h in list(self._routable()):
                if h.killed:
                    h.missed_beats += 1
                    if h.missed_beats >= self.miss_beats:
                        self._declare_dead(h)
            self._place()
            for h in list(self._routable()):
                if h.killed:
                    continue
                try:
                    # a process pool has no local scope — its engine
                    # state lives across the RPC boundary
                    with (scope_guard(h.scope) if h.scope is not None
                          else nullcontext()):
                        done = h.engine.step()
                except Exception as e:  # dead step thread: fail over NOW
                    print("FABRIC POOL STEP DIED pid=%d step=%d: %r"
                          % (h.pid, self.now, e), flush=True)
                    self._declare_dead(h)
                    continue
                h.missed_beats = 0
                if done:
                    self._harvest(h, done)
                    terminal.extend(done)
                if (h.state == "draining" and not h.engine.queue
                        and not h.engine.pool.active_slots()):
                    h.state = "retired"
                    self.counters["pools_retired"] += 1
                    print("FABRIC POOL RETIRED pid=%d step=%d"
                          % (h.pid, self.now), flush=True)
                    self.pools.pop(h.pid, None)
                    if isinstance(h.engine, ProcessPool):
                        # graceful worker shutdown: drain-and-retire
                        # must not leave an orphan process behind
                        h.engine.close(kill=False)
            self.now += 1
            return terminal

    def _maybe_inject_fault(self):
        """One fault-schedule slot per fabric step ('fabric' direction):
        a pool_kill action kills one live pool — an explicit
        'pool_kill:<pid>' names the victim, a bare 'pool_kill' picks one
        deterministically from the schedule's seeded per-frame hash.
        `pool_proc_kill` is the process-mode twin: a REAL SIGKILL on
        the pool's worker process, detected by the RPC failure path."""
        if self.faults is None:
            return
        idx, action = self.faults.next_action("fabric")
        base, _, arg = str(action).partition(":")
        if base not in ("pool_kill", "pool_proc_kill"):
            return
        live = sorted(self._live(), key=lambda h: h.pid)
        if not live:
            return
        if arg:
            pid = int(arg)
            if pid not in self.pools:
                return
        else:
            pick = int(self.faults.delay_fraction(idx) * len(live))
            pid = live[pick % len(live)].pid
        if base == "pool_proc_kill":
            self.proc_kill_pool(pid)
        else:
            self.kill_pool(pid)

    # ---- control plane -------------------------------------------------
    def stats(self):
        """The supervisor's shared signal set — same verb shape the
        PR 15 pserver scaler polls: fabric queue depth, mean live-pool
        occupancy, cumulative rejection / re-placement counters (the
        poller diffs them), and per-pool detail."""
        with self._lock:
            live = self._live()
            occ = (sum(len(h.engine.pool.active_slots())
                       / float(h.engine.n_slots) for h in live)
                   / len(live)) if live else 0.0
            sub = max(1, self.counters["submitted"])
            per_pool = {}
            for h in self.pools.values():
                c = h.engine.counters
                prop = int(c.get("spec_proposed", 0))
                acc = int(c.get("spec_accepted", 0))
                per_pool[str(h.pid)] = {
                    "state": h.state,
                    "active_slots": len(h.engine.pool.active_slots()),
                    "n_slots": h.engine.n_slots,
                    "backlog": len(h.engine.queue),
                    "compile_count": h.engine.exe.compile_count,
                    # run-MEAN slot occupancy (the engine accumulates
                    # per step) — the instantaneous active_slots reads
                    # 0 at any quiesced boundary
                    "mean_occupancy": round(
                        c["occupancy_sum"] / max(1, c["steps"]), 4),
                    # the fast-path signal set: draft acceptance and
                    # prefix reuse per pool (the supervisor's scaler
                    # and the bench read these through the same verb)
                    "spec_proposed": prop,
                    "spec_accepted": acc,
                    "accept_rate": round(acc / float(prop), 4)
                    if prop else 1.0,
                    "prefix_hits": int(c.get("prefix_hits", 0)),
                    "prefix_misses": int(c.get("prefix_misses", 0)),
                    "prefix_tokens_reused": int(
                        c.get("prefix_tokens_reused", 0)),
                }
            s = dict(self.counters)
            s.update({
                "n_pools": len(live),
                "queue_depth": len([q for q in self.queue
                                    if q.arrival <= self.now]),
                "occupancy": round(occ, 4),
                "rejection_rate": round(
                    self.counters["rejected"] / float(sub), 4),
                "step": self.now,
                "prefixes_registered": len(self._prefixes),
                "pools": per_pool,
            })
            return s

    def control_service(self):
        """A make_var_server-compatible service: the router side of the
        unified control plane.  Verbs: stats, scale_pools(delta),
        drain_pool(pid), kill_pool(pid), attach_worker(endpoint),
        report_pool_death(pid|endpoint) — scale/drain/kill mutate via
        request_scale/flags so the stepping thread applies them at a
        step boundary; attach/death-report are the supervisor's
        process-mode spawn and on_child_death hooks."""
        router = self

        class _Control:
            def handle(self, verb, **kw):
                # errors ship to the client as {"__error__": ...} (the
                # pserver convention): raising here would only drop the
                # connection and surface as a retry timeout
                try:
                    if verb == "stats":
                        return router.stats()
                    if verb == "scale_pools":
                        router.request_scale(int(kw.get("delta", 0)))
                        return {"ok": True,
                                "n_pools": len(router._live())}
                    if verb == "drain_pool":
                        with router._lock:
                            router.drain_pool(int(kw["pid"]))
                        return {"ok": True}
                    if verb == "kill_pool":
                        with router._lock:
                            router.kill_pool(int(kw["pid"]))
                        return {"ok": True}
                    if verb == "attach_worker":
                        pid = router.attach_worker(kw["endpoint"])
                        return {"ok": True, "pid": pid}
                    if verb == "register_prefix":
                        rows = router.register_prefix(kw["tokens"])
                        return {"ok": True,
                                "rows": {str(k): int(v)
                                         for k, v in rows.items()}}
                    if verb == "report_pool_death":
                        hit = router.report_worker_death(
                            pid=kw.get("pid"),
                            endpoint=kw.get("endpoint"))
                        return {"ok": True, "found": bool(hit)}
                    raise ValueError(
                        "unknown fabric verb %r" % (verb,))
                except Exception as e:
                    return {"__error__": "%s" % (e,)}

        return _Control()

    def serve_control(self, endpoint="127.0.0.1:0"):
        """Expose the control plane over RPC (threaded VarServer): the
        remote half of launch.py's --serve-router supervision."""
        from ..distributed.rpc import make_var_server

        srv = make_var_server(endpoint, self.control_service())
        srv.start()
        return srv

    # ---- episode driver ------------------------------------------------
    def run(self, requests=None, max_steps=100000, pool_schedule=None):
        """Serve `requests` to completion (plus anything queued).
        `pool_schedule` = [(fabric_step, delta)] or a 'T:+N,T:-N'
        string — the deterministic chaos/bench driver riding the exact
        scale_pools machinery the supervisor uses.  Returns (results,
        stats)."""
        if isinstance(pool_schedule, str):
            pool_schedule = parse_pool_schedule(pool_schedule)
        sched = sorted(pool_schedule or [], key=lambda e: e[0])
        for r in requests or []:
            self.submit(r)
        t0 = time.time()
        while True:
            with self._lock:
                busy = bool(self.queue) or any(
                    h.engine.queue or h.engine.pool.active_slots()
                    for h in self._routable())
                pending = bool(sched) or bool(self._pending_scale)
            if not busy and not pending:
                break
            while sched and sched[0][0] <= self.now:
                self.scale_pools(sched.pop(0)[1])
            self.step()
            if self.now >= max_steps:
                raise RuntimeError(
                    "fabric exceeded max_steps=%d with work pending"
                    % max_steps)
        wall = time.time() - t0
        stats = self.stats()
        stats["wall_s"] = round(wall, 4)
        new_tokens = sum(
            int(np.asarray(r["tokens"]).size)
            for r in self._results.values() if r["status"] == "OK")
        stats["new_tokens"] = new_tokens
        stats["tokens_per_s"] = (round(new_tokens / wall, 1)
                                 if wall else 0.0)
        return dict(self._results), stats
