"""Continuous-batching serving engine (the Orca/vLLM-style scheduler
over this repo's decode-cache stack).

ONE compiled ragged wide-step program (gpt2_ragged_step_program: width
W over a fixed pool of B cache slots) serves every request.  Each
engine step the scheduler

  1. admits queued requests (arrival <= now) into free slots, zeroing
     just those slots' cache rows via the slot-reset program (the
     add_cache_zero_fills machinery generalized to per-slot resets —
     one compiled program for ANY subset of slots),
  2. dispatches the pooled step: prompt-prefill chunks for newly
     admitted requests INTERLEAVED with one-token decode for in-flight
     ones (per-slot pos/width vectors drive slot_cache_write and the
     per-row offset-causal qstart mask),
  3. samples each due row host-side with that request's OWN params and
     rng key (temperature/top-k/top-p vectors + fold_in(seed, step) —
     decode_cache.filtered_probs_rows / sample_rows_keyed),
  4. evicts finished/EOS slots immediately (free for next step's
     admissions).

Exactness contract: every request's emitted tokens are bit-identical
to its solo run through the SAME engine (greedy, and sampled given the
same per-request seed), regardless of what shares the batch or when it
was admitted — row-independent math in the pooled program plus
per-request sampling keys.  Occupancy changes only change feed VALUES,
never shapes, so the step compiles exactly once
(Executor.compile_count pins this in tests).  Boundary: a bf16 KV
cache stays a documented precision/memory tradeoff — engine-vs-solo
equality still holds (both run the same bf16 program), but neither
matches the f32-cache chain bit-for-bit.
"""

import time

import numpy as np

from ..profiler import RecordEvent

__all__ = ["ServingEngine", "serve_one_at_a_time"]


class ServingEngine:
    """exe: Executor whose scope already holds the model weights (the
    ragged program shares parameter names with gpt2_lm_program /
    gpt2_logits_program built in the same process — run one of their
    startups, or load a checkpoint, before serving)."""

    def __init__(self, exe, hp, n_slots=4, width=8, t_max=None,
                 cache_dtype="float32", quantize_int8=False,
                 queue_depth=None, mesh=None, partition_rules=None,
                 mp_axis=None):
        from ..models import gpt2
        from ..models.decode_cache import make_slot_reset_program
        from .pool import SlotPool

        self.exe = exe
        self.hp = hp
        self.n_slots = int(n_slots)
        self.width = int(width)
        self.t_max = int(t_max or hp.n_ctx)
        self.cache_dtype = cache_dtype
        (self.step_main, self.cache_startup, self._feeds, self.step_fetch,
         self.cache_names) = gpt2.gpt2_ragged_step_program(
            hp, batch=self.n_slots, t_max=self.t_max, width=self.width,
            cache_dtype=cache_dtype)
        if quantize_int8:
            # weight-only int8 serving: per-tensor matmul weights +
            # per-row embedding tables, dequant fused into the step
            from ..contrib.quantize.quantize_transpiler import (
                quantize_weights_int8,
            )

            quantize_weights_int8(self.step_main)
        n_kv = getattr(hp, "n_kv_head", None) or hp.n_head
        dh = hp.d_model // hp.n_head
        self.reset_prog = make_slot_reset_program(
            [(n, (self.n_slots, n_kv, self.t_max, dh)) for n in
             self.cache_names],
            self.n_slots, dtype=cache_dtype)
        # tensor-parallel pool (GSPMD over `mesh`): stamp EVERY program
        # touching the slot-pool persistables — step, per-slot reset,
        # cache startup — with the partition-rule table, so the pool
        # lives sharded in HBM end to end (a single unstamped program
        # would pull the sharded caches back onto one device).  The
        # rule table resolves from the model config's partition_family
        # unless given explicitly; the first mesh axis hosts the model
        # dimension unless mp_axis names one.
        self.mesh = mesh
        self.partition_rules = None
        if mesh is not None:
            from ..parallel.partition_rules import (
                annotate_spmd,
                partition_rules_for,
            )

            if partition_rules is None:
                axis = mp_axis or ("mp" if "mp" in mesh.axis_names
                                   else mesh.axis_names[0])
                partition_rules = partition_rules_for(
                    getattr(hp, "partition_family", "gpt2"), mp_axis=axis)
            self.partition_rules = partition_rules
            for prog in (self.step_main, self.cache_startup,
                         self.reset_prog):
                annotate_spmd(prog, mesh, partition_rules)
        self.pool = SlotPool(self.n_slots, self.width, self.t_max)
        self.queue = []  # submitted, not yet admitted (arrival order)
        # admission control: an ARRIVAL that finds `queue_depth`
        # requests already waiting is rejected loudly with a terminal
        # REJECTED_QUEUE_FULL instead of queueing unboundedly (None =
        # the legacy unbounded queue).  Requests submitted before their
        # arrival step don't count — the bound is on the WAIT queue.
        self.queue_depth = None if queue_depth is None else int(queue_depth)
        assert self.queue_depth is None or self.queue_depth >= 0
        self.now = 0
        self.counters = {"steps": 0, "admitted": 0, "finished": 0,
                         "new_tokens": 0, "occupancy_sum": 0.0,
                         "prefill_steps": 0, "decode_steps": 0,
                         "rejected": 0, "expired": 0}
        self._step_wall = []
        self._results = {}

    # ---- request intake ------------------------------------------------
    def submit(self, req):
        self.pool.validate(req)
        live = {q.rid for q in self.queue}
        live.update(s.req.rid for _, s in self.pool.active_slots())
        if req.rid in live:
            raise ValueError("duplicate request id %r" % (req.rid,))
        self.queue.append(req)
        self.queue.sort(key=lambda r: (r.arrival, r.rid))

    # ---- one scheduler iteration --------------------------------------
    def _terminal(self, req, status, slot_state=None):
        """Record a terminal (non-OK) outcome: rejected at admission or
        expired while queued/mid-decode.  Loud by design — admission
        control failing silently is how queues grow unboundedly."""
        self.counters["rejected" if status == "REJECTED_QUEUE_FULL"
                      else "expired"] += 1
        print("SERVE %s rid=%r step=%d" % (status, req.rid, self.now),
              flush=True)
        # terminal results carry the SAME shape as OK results (latency
        # measured to the terminal step): consumers that sweep
        # results.values() — bench latency percentiles included — must
        # not need to special-case by status
        wall = time.time()
        a = min(req.arrival_step, max(0, len(self._step_wall) - 1))
        self._results[req.rid] = {
            "tokens": np.asarray(
                slot_state.out if slot_state is not None else [],
                "int64"),
            "prompt_len": int(req.prompt.size),
            "arrival_step": req.arrival_step,
            "admit_step": (slot_state.admit_step
                           if slot_state is not None else None),
            "finish_step": self.now,
            "status": status,
            "latency_steps": self.now - req.arrival_step + 1,
            "latency_s": wall - (self._step_wall[a] if self._step_wall
                                 else wall),
        }

    def step(self):
        """Admit -> pooled dispatch -> sample -> evict.  Returns the
        list of request ids that reached a TERMINAL state this step:
        finished, deadline-expired, or rejected at admission — a
        step-by-step driver harvesting results by this list must see
        every outcome, not just the happy one."""
        terminal = []
        with RecordEvent("serve_admit", cat="admit"):
            # per-request deadlines sweep FIRST: an expired mid-decode
            # slot frees for THIS step's admissions, and an expired
            # waiter must not take a slot ahead of live requests
            for slot, s in self.pool.active_slots():
                d = s.req.deadline
                if d is not None and self.now >= s.req.arrival_step + d:
                    self.pool.evict(slot)
                    self._terminal(s.req, "DEADLINE_EXPIRED", s)
                    terminal.append(s.req.rid)
            keep = np.ones(self.n_slots, "float32")
            admitted = False
            waiting = 0
            still = []
            for req in self.queue:  # arrival order (submit keeps it)
                d = req.deadline
                if req.arrival > self.now:
                    still.append(req)
                elif d is not None and self.now >= req.arrival_step + d:
                    self._terminal(req, "DEADLINE_EXPIRED")
                    terminal.append(req.rid)
                elif self.pool.free_slots():
                    slot = self.pool.admit(req, self.now)
                    keep[slot] = 0.0
                    admitted = True
                    self.counters["admitted"] += 1
                elif (self.queue_depth is None
                      or waiting < self.queue_depth):
                    waiting += 1
                    still.append(req)
                else:
                    # the wait queue is at depth: this arrival is
                    # rejected NOW, not queued unboundedly
                    self._terminal(req, "REJECTED_QUEUE_FULL")
                    terminal.append(req.rid)
            self.queue = still
            if admitted:
                # zero exactly the admitted slots' cache rows; one
                # compiled program regardless of WHICH slots reset
                self.exe.run(self.reset_prog, feed={"slot_keep": keep},
                             fetch_list=[])
        active = self.pool.active_slots()
        if not active:
            self.now += 1
            return terminal
        feed, plan = self.pool.build_feed(self.hp.n_ctx)
        prefilling = self.pool.any_prefilling()
        phase = "prefill" if prefilling else "decode"
        self.counters[phase + "_steps"] += 1
        with RecordEvent("serve_step", cat=phase):
            (logits,) = self.exe.run(self.step_main, feed=feed,
                                     fetch_list=self.step_fetch)
        logits = np.asarray(logits)
        finished = []
        with RecordEvent("serve_sample", cat="sample"):
            # slots whose chunk did not finish a prompt just advance
            due = {slot for slot, _ in plan}
            for slot, s in active:
                if slot not in due:
                    self.pool.advance_prefill(slot)
            if plan:
                rows = np.stack([logits[slot, col] for slot, col in plan])
                toks = self._pick_tokens(rows, [s for s, _ in plan])
                for (slot, _), tok in zip(plan, toks):
                    s = self.pool.slots[slot]
                    done = self.pool.advance(slot, tok)
                    self.counters["new_tokens"] += 1
                    if done:
                        self._finish(slot)
                        finished.append(s.req.rid)
        self.counters["steps"] += 1
        self.counters["occupancy_sum"] += len(active) / self.n_slots
        self.now += 1
        return terminal + finished

    def _pick_tokens(self, rows, slots):
        """Per-row token selection with PER-REQUEST params, VECTORIZED
        over the due rows (PR 9's documented "loops per row" limit
        closed): greedy rows argmax in one batched pass, sampled rows
        run ONE batched filtered_probs_rows (itself vectorized, bit-
        identical to the per-row chain) and draw with
        fold_in(seed, request_step) keys — a pure function of
        (request, step), neighbors invisible."""
        from ..models.decode_cache import (
            filtered_probs_rows,
            sample_rows_keyed,
        )

        rows = np.asarray(rows)
        sl = [self.pool.slots[s] for s in slots]
        greedy = np.array([s.req.greedy for s in sl], bool)
        out = np.zeros(len(slots), "int64")
        if greedy.any():
            out[greedy] = rows[greedy].argmax(axis=-1)
        samp = np.nonzero(~greedy)[0]
        if samp.size:
            ss = [sl[j] for j in samp]
            probs = filtered_probs_rows(
                rows[samp],
                [s.req.temperature for s in ss],
                [s.req.top_k for s in ss],
                [s.req.top_p for s in ss])
            toks = sample_rows_keyed(
                probs,
                [s.req.seed for s in ss],
                # request_step = GLOBAL token index: a failover-replayed
                # request (router) carries the dead pool's emitted
                # prefix inside its prompt and offsets the key base past
                # it, so the continuation draws the solo run's tokens
                [len(s.out) + getattr(s.req, "sample_step_base", 0)
                 for s in ss])
            out[samp] = toks
        return out

    def _finish(self, slot):
        s = self.pool.evict(slot)
        self.counters["finished"] += 1
        wall = time.time()
        a = min(s.req.arrival_step, max(0, len(self._step_wall) - 1))
        self._results[s.req.rid] = {
            "tokens": np.asarray(s.out, "int64"),
            "prompt_len": int(s.req.prompt.size),
            "arrival_step": s.req.arrival_step,
            "admit_step": s.admit_step,
            "finish_step": self.now,
            "status": "OK",
            "latency_steps": self.now - s.req.arrival_step + 1,
            "latency_s": wall - (self._step_wall[a] if self._step_wall
                                 else wall),
        }

    # ---- result serialization (out-of-process pools) -------------------
    @staticmethod
    def wire_result(r):
        """One terminal result coerced onto the RPC wire's closed type
        system: tokens stay an int64 ndarray (wire-native), every scalar
        is forced to a plain int/float/str/None — a stray np.int64
        leaking into finish_step would fail the codec, and the statuses
        (OK / DEADLINE_EXPIRED / REJECTED_QUEUE_FULL) must cross the
        wire unchanged for the router's backpressure accounting."""

        def _scalar(v):
            if v is None or isinstance(v, (str, bool)):
                return v
            if isinstance(v, (int, np.integer)):
                return int(v)
            return float(v)

        out = {}
        for k, v in r.items():
            if k == "tokens":
                out[k] = np.asarray(v, "int64")
            else:
                out[k] = _scalar(v)
        return out

    def wire_results(self, rids=None):
        """Terminal results for `rids` (default: all) as wire-safe
        dicts, each tagged with its "rid" — the pool worker's `step` /
        `results` reply payload."""
        keys = self._results.keys() if rids is None else rids
        out = []
        for rid in keys:
            r = self.wire_result(self._results[rid])
            r["rid"] = rid
            out.append(r)
        return out

    # ---- pool placement accounting -------------------------------------
    def kv_pool_bytes(self, scope=None):
        """Where the KV slot-pool actually lives: total pool bytes, the
        per-device resident bytes (dedup'd by shard index, so a
        replicated pool reports its full size on EVERY device), and
        their max — the tensor-parallel acceptance number is
        max_device_bytes / total_bytes ~ 1/N on the heads axis.  Call
        after a run (the caches must exist in the scope)."""
        import jax

        from ..core.scope import global_scope

        scope = scope or global_scope()
        total = 0
        per_dev = {}
        for n in self.cache_names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    "kv_pool_bytes: cache %r not in scope — run the "
                    "engine (or its cache startup) first" % n)
            arr = v if isinstance(v, jax.Array) else np.asarray(v)
            nbytes = int(np.prod(arr.shape)) * arr.dtype.itemsize
            total += nbytes
            shards = getattr(arr, "addressable_shards", None)
            if not shards:
                per_dev["host"] = per_dev.get("host", 0) + nbytes
                continue
            for s in shards:
                d = str(s.device)
                per_dev[d] = per_dev.get(d, 0) + int(s.data.size
                                                     * s.data.itemsize)
        return {
            "total_bytes": total,
            "per_device_bytes": per_dev,
            "max_device_bytes": max(per_dev.values()) if per_dev else 0,
        }

    # ---- episode drivers ----------------------------------------------
    def run(self, requests=None, max_steps=100000):
        """Serve `requests` (plus anything already queued) to
        completion: zero the caches, loop step() until drained.
        Returns (results, stats) — results keyed by request id with the
        emitted tokens and per-request latency, stats the aggregate
        COUNTERS-style dict (sustained tokens/s, occupancy %, step
        phase counts, mean step seconds)."""
        self.now = 0
        self._step_wall = []
        self._results = {}
        for k in self.counters:
            self.counters[k] = 0
        for r in requests or []:
            self.submit(r)
        self.exe.run(self.cache_startup)
        t0 = time.time()
        while self.queue or self.pool.active_slots():
            self._step_wall.append(time.time())
            self.step()
            if self.now >= max_steps:
                # drain the wedge before raising: a poisoned episode
                # must not leave slots occupied (run_solo would forever
                # see a busy engine and resubmits would look duplicate)
                n_left = len(self.queue) + len(self.pool.active_slots())
                self.queue = []
                for slot, _ in self.pool.active_slots():
                    self.pool.evict(slot)
                raise RuntimeError(
                    "serving engine exceeded max_steps=%d with %d "
                    "requests unfinished (state cleared; finished "
                    "results discarded)" % (max_steps, n_left))
        wall = time.time() - t0
        c = dict(self.counters)
        steps = max(1, c.pop("steps"))
        stats = {
            "steps": steps,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(c["new_tokens"] / wall, 1) if wall else 0.0,
            "occupancy_pct": round(100.0 * c.pop("occupancy_sum") / steps, 1),
            "step_s_mean": wall / steps,
            "compile_count": self.exe.compile_count,
        }
        stats.update(c)
        return self._results, stats

    def run_solo(self, req):
        """Serve ONE request through the same pooled program with every
        other slot free — the exactness reference and the
        serve-one-at-a-time baseline unit.  Returns (tokens, stats)."""
        if self.queue or self.pool.active_slots():
            raise RuntimeError("run_solo on a busy engine")
        from .trace import Request

        solo = Request(rid=req.rid, prompt=req.prompt,
                       max_new_tokens=req.max_new_tokens,
                       temperature=req.temperature, top_k=req.top_k,
                       top_p=req.top_p, seed=req.seed, eos_id=req.eos_id,
                       arrival=0.0)
        results, stats = self.run([solo])
        return results[req.rid]["tokens"], stats


def serve_one_at_a_time(engine, requests, arrival_step_seconds=None):
    """The A/B baseline: the same trace served sequentially, each
    request owning the whole pool (run_solo) — what serving looked like
    before the scheduler.  Throughput = total new tokens over total
    service wall time.  Latency replays the virtual arrival clock:
    arrivals map to seconds via `arrival_step_seconds` (pass the
    engine's measured mean step seconds so both systems face the same
    arrival process), each request starts at max(its arrival, the
    previous finish) and waits in the FIFO queue — the queueing delay
    continuous batching exists to remove.  Returns (results, stats)."""
    results = {}
    svc_total = 0.0
    tokens_total = 0
    step_s = float(arrival_step_seconds or 0.0)
    finish_v = 0.0
    for req in sorted(requests, key=lambda r: (r.arrival, r.rid)):
        t0 = time.time()
        tokens, _ = engine.run_solo(req)
        svc = time.time() - t0
        svc_total += svc
        tokens_total += int(tokens.size)
        arrive_v = req.arrival_step * step_s
        finish_v = max(arrive_v, finish_v) + svc
        results[req.rid] = {
            "tokens": tokens,
            "prompt_len": int(req.prompt.size),
            "latency_s": finish_v - arrive_v,
            "service_s": svc,
        }
    stats = {
        "wall_s": round(svc_total, 4),
        "tokens_per_s": (round(tokens_total / svc_total, 1)
                         if svc_total else 0.0),
        "new_tokens": tokens_total,
    }
    return results, stats
