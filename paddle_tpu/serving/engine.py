"""Continuous-batching serving engine (the Orca/vLLM-style scheduler
over this repo's decode-cache stack).

ONE compiled ragged wide-step program (gpt2_ragged_step_program: width
W over a fixed pool of B cache slots) serves every request.  Each
engine step the scheduler

  1. admits queued requests (arrival <= now) into free slots, zeroing
     just those slots' cache rows via the slot-reset program (the
     add_cache_zero_fills machinery generalized to per-slot resets —
     one compiled program for ANY subset of slots),
  2. dispatches the pooled step: prompt-prefill chunks for newly
     admitted requests INTERLEAVED with one-token decode for in-flight
     ones (per-slot pos/width vectors drive slot_cache_write and the
     per-row offset-causal qstart mask),
  3. samples each due row host-side with that request's OWN params and
     rng key (temperature/top-k/top-p vectors + fold_in(seed, step) —
     decode_cache.filtered_probs_rows / sample_rows_keyed),
  4. evicts finished/EOS slots immediately (free for next step's
     admissions).

Exactness contract: every request's emitted tokens are bit-identical
to its solo run through the SAME engine (greedy, and sampled given the
same per-request seed), regardless of what shares the batch or when it
was admitted — row-independent math in the pooled program plus
per-request sampling keys.  Occupancy changes only change feed VALUES,
never shapes, so the step compiles exactly once
(Executor.compile_count pins this in tests).  Boundary: a bf16 KV
cache stays a documented precision/memory tradeoff — engine-vs-solo
equality still holds (both run the same bf16 program), but neither
matches the f32-cache chain bit-for-bit.
"""

import time

import numpy as np

from ..profiler import RecordEvent
from .pool import DECODE, PREFILL

__all__ = ["ServingEngine", "serve_one_at_a_time"]


def _accept_rate(accepted, proposed):
    """Acceptance rate with the solo core's convention: no proposals
    (spec off / pure-prefill request) reads 1.0."""
    return (accepted / proposed) if proposed else 1.0


class ServingEngine:
    """exe: Executor whose scope already holds the model weights (the
    ragged program shares parameter names with gpt2_lm_program /
    gpt2_logits_program built in the same process — run one of their
    startups, or load a checkpoint, before serving)."""

    def __init__(self, exe, hp, n_slots=4, width=8, t_max=None,
                 cache_dtype="float32", quantize_int8=False,
                 queue_depth=None, mesh=None, partition_rules=None,
                 mp_axis=None, draft=None, spec_k=None, prefix_rows=0,
                 prefix_chunk=None):
        from ..models import gpt2
        from ..models.decode_cache import make_slot_reset_program
        from .pool import SlotPool

        self.exe = exe
        self.hp = hp
        self.n_slots = int(n_slots)
        self.width = int(width)
        self.t_max = int(t_max or hp.n_ctx)
        self.cache_dtype = cache_dtype
        (self.step_main, self.cache_startup, self._feeds, self.step_fetch,
         self.cache_names) = gpt2.gpt2_ragged_step_program(
            hp, batch=self.n_slots, t_max=self.t_max, width=self.width,
            cache_dtype=cache_dtype)
        if quantize_int8:
            # weight-only int8 serving: per-tensor matmul weights +
            # per-row embedding tables, dequant fused into the step
            from ..contrib.quantize.quantize_transpiler import (
                quantize_weights_int8,
            )

            quantize_weights_int8(self.step_main)
        n_kv = getattr(hp, "n_kv_head", None) or hp.n_head
        dh = hp.d_model // hp.n_head
        self.reset_prog = make_slot_reset_program(
            [(n, (self.n_slots, n_kv, self.t_max, dh)) for n in
             self.cache_names],
            self.n_slots, dtype=cache_dtype)
        # ---- in-pool speculative decoding ----------------------------
        # draft = "self" hosts the TARGET model's own ragged step over a
        # SECOND KV pool in the same scope (cache_prefix renames the
        # persistables) — spec_k-token verify chunks with zero extra
        # weights; draft = (draft_hp, draft_scope) hosts a SMALL draft
        # model (its own weights + caches in its own fluid.Scope) over
        # the SAME slot layout.  Either way the draft program's feed
        # contract is the target's, so the engine's pooled feed drives
        # both and the slot lifecycle (admit/reset/evict) covers the
        # draft pool for free.
        self.draft_hp = None
        self.draft_scope = None
        self.spec_k = 0
        if draft is not None:
            if isinstance(draft, str):
                assert draft == "self", draft
                self.draft_hp, self.draft_scope = hp, None
                dprefix = "gpt2sd"
            else:
                self.draft_hp, self.draft_scope = draft
                dprefix = "gpt2"
            self.spec_k = int(spec_k or min(4, self.width))
            assert 2 <= self.spec_k <= self.width, (
                "spec_k must be in [2, width]", self.spec_k, self.width)
            assert self.draft_hp.n_ctx >= self.t_max, (
                self.draft_hp.n_ctx, self.t_max)
            (self.draft_main, self.draft_startup, _df, self.draft_fetch,
             self.draft_cache_names) = gpt2.gpt2_ragged_step_program(
                self.draft_hp, batch=self.n_slots, t_max=self.t_max,
                width=self.width, cache_dtype=cache_dtype,
                cache_prefix=dprefix)
            dn_kv = (getattr(self.draft_hp, "n_kv_head", None)
                     or self.draft_hp.n_head)
            ddh = self.draft_hp.d_model // self.draft_hp.n_head
            self._draft_slot_shape = (self.n_slots, dn_kv, self.t_max, ddh)
            self.draft_reset = make_slot_reset_program(
                [(n, self._draft_slot_shape)
                 for n in self.draft_cache_names],
                self.n_slots, dtype=cache_dtype)
        # ---- prefix-cache KV reuse -----------------------------------
        # A row pool of registered common prompt prefixes; admission
        # longest-matches on token ids and a compiled row-copy program
        # moves the matched KV into the slot so prefill starts AT the
        # boundary.  chunk must be a multiple of the dispatch width so
        # a resumed prefill replays the cold chunk schedule bit-exactly.
        # A speculative engine mirrors every prefix row in a DRAFT bank:
        # the draft distribution must resume exactly too, or sampled
        # accept/reject draws would fork prefix-hit streams from cold.
        self.prefix = None
        self.prefix_chunk = 0
        if prefix_rows:
            from .prefix import PrefixCache

            chunk = int(prefix_chunk or self.width)
            assert chunk % self.width == 0, (chunk, self.width)
            self.prefix = PrefixCache(int(prefix_rows), chunk)
            self.prefix_chunk = chunk
            self.prefix.add_bank(
                self.cache_names, (self.n_slots, n_kv, self.t_max, dh),
                cache_dtype, tag="target")
            if self.spec_k:
                self.prefix.add_bank(
                    self.draft_cache_names, self._draft_slot_shape,
                    cache_dtype, tag="draft", scope=self.draft_scope)
        # tensor-parallel pool (GSPMD over `mesh`): stamp EVERY program
        # touching the slot-pool persistables — step, per-slot reset,
        # cache startup, the draft pool's trio, and the prefix-cache
        # copy programs — with the partition-rule table, so the pool
        # lives sharded in HBM end to end (a single unstamped program
        # would pull the sharded caches back onto one device).  The
        # rule table resolves from the model config's partition_family
        # unless given explicitly; the first mesh axis hosts the model
        # dimension unless mp_axis names one.
        self.mesh = mesh
        self.partition_rules = None
        if mesh is not None:
            from ..parallel.partition_rules import (
                annotate_spmd,
                partition_rules_for,
            )

            if partition_rules is None:
                axis = mp_axis or ("mp" if "mp" in mesh.axis_names
                                   else mesh.axis_names[0])
                partition_rules = partition_rules_for(
                    getattr(hp, "partition_family", "gpt2"), mp_axis=axis)
            self.partition_rules = partition_rules
            progs = [self.step_main, self.cache_startup, self.reset_prog]
            if self.spec_k:
                progs += [self.draft_main, self.draft_startup,
                          self.draft_reset]
            if self.prefix is not None:
                for bank in self.prefix.banks:
                    progs += [bank.load_prog, bank.store_prog,
                              bank.startup]
            for prog in progs:
                annotate_spmd(prog, mesh, partition_rules)
        if self.prefix is not None:
            # zero-fill the prefix pools ONCE — registered rows persist
            # across serving episodes (run() only re-zeroes slot pools)
            self.prefix.startup(self.exe)
        self.pool = SlotPool(self.n_slots, self.width, self.t_max)
        self.queue = []  # submitted, not yet admitted (arrival order)
        # admission control: an ARRIVAL that finds `queue_depth`
        # requests already waiting is rejected loudly with a terminal
        # REJECTED_QUEUE_FULL instead of queueing unboundedly (None =
        # the legacy unbounded queue).  Requests submitted before their
        # arrival step don't count — the bound is on the WAIT queue.
        self.queue_depth = None if queue_depth is None else int(queue_depth)
        assert self.queue_depth is None or self.queue_depth >= 0
        self.now = 0
        self.counters = {"steps": 0, "admitted": 0, "finished": 0,
                         "new_tokens": 0, "occupancy_sum": 0.0,
                         "prefill_steps": 0, "decode_steps": 0,
                         "rejected": 0, "expired": 0,
                         "prefill_chunks": 0, "draft_steps": 0,
                         "spec_rounds": 0, "spec_proposed": 0,
                         "spec_accepted": 0, "prefix_hits": 0,
                         "prefix_misses": 0, "prefix_tokens_reused": 0}
        self._step_wall = []
        self._results = {}

    # ---- request intake ------------------------------------------------
    def submit(self, req):
        self.pool.validate(req)
        live = {q.rid for q in self.queue}
        live.update(s.req.rid for _, s in self.pool.active_slots())
        if req.rid in live:
            raise ValueError("duplicate request id %r" % (req.rid,))
        self.queue.append(req)
        self.queue.sort(key=lambda r: (r.arrival, r.rid))

    # ---- one scheduler iteration --------------------------------------
    def _terminal(self, req, status, slot_state=None):
        """Record a terminal (non-OK) outcome: rejected at admission or
        expired while queued/mid-decode.  Loud by design — admission
        control failing silently is how queues grow unboundedly."""
        self.counters["rejected" if status == "REJECTED_QUEUE_FULL"
                      else "expired"] += 1
        print("SERVE %s rid=%r step=%d" % (status, req.rid, self.now),
              flush=True)
        # terminal results carry the SAME shape as OK results (latency
        # measured to the terminal step): consumers that sweep
        # results.values() — bench latency percentiles included — must
        # not need to special-case by status
        wall = time.time()
        a = min(req.arrival_step, max(0, len(self._step_wall) - 1))
        self._results[req.rid] = {
            "tokens": np.asarray(
                slot_state.out if slot_state is not None else [],
                "int64"),
            "prompt_len": int(req.prompt.size),
            "arrival_step": req.arrival_step,
            "admit_step": (slot_state.admit_step
                           if slot_state is not None else None),
            "finish_step": self.now,
            "status": status,
            "latency_steps": self.now - req.arrival_step + 1,
            "latency_s": wall - (self._step_wall[a] if self._step_wall
                                 else wall),
            "prefix_len": getattr(slot_state, "prefix_len", 0),
            "spec_proposed": getattr(slot_state, "spec_proposed", 0),
            "spec_accepted": getattr(slot_state, "spec_accepted", 0),
            "accept_rate": _accept_rate(
                getattr(slot_state, "spec_accepted", 0),
                getattr(slot_state, "spec_proposed", 0)),
        }

    def step(self):
        """Admit -> pooled dispatch -> sample -> evict.  Returns the
        list of request ids that reached a TERMINAL state this step:
        finished, deadline-expired, or rejected at admission — a
        step-by-step driver harvesting results by this list must see
        every outcome, not just the happy one."""
        terminal = []
        with RecordEvent("serve_admit", cat="admit"):
            # per-request deadlines sweep FIRST: an expired mid-decode
            # slot frees for THIS step's admissions, and an expired
            # waiter must not take a slot ahead of live requests
            for slot, s in self.pool.active_slots():
                d = s.req.deadline
                if d is not None and self.now >= s.req.arrival_step + d:
                    self.pool.evict(slot)
                    self._terminal(s.req, "DEADLINE_EXPIRED", s)
                    terminal.append(s.req.rid)
            keep = np.ones(self.n_slots, "float32")
            admitted = False
            loads = {}  # slot -> prefix row (this wave's prefix hits)
            waiting = 0
            still = []
            for req in self.queue:  # arrival order (submit keeps it)
                d = req.deadline
                if req.arrival > self.now:
                    still.append(req)
                elif d is not None and self.now >= req.arrival_step + d:
                    self._terminal(req, "DEADLINE_EXPIRED")
                    terminal.append(req.rid)
                elif self.pool.free_slots():
                    pfx_row, pfx_len = (None, 0)
                    if self.prefix is not None:
                        pfx_row, pfx_len = self.prefix.match(req.prompt)
                    slot = self.pool.admit(req, self.now,
                                           prefix_len=pfx_len)
                    keep[slot] = 0.0
                    admitted = True
                    self.counters["admitted"] += 1
                    if pfx_row is not None:
                        loads[slot] = pfx_row
                        self.prefix.touch(pfx_row, pfx_len)
                        self.counters["prefix_hits"] += 1
                        self.counters["prefix_tokens_reused"] += pfx_len
                    elif self.prefix is not None:
                        self.prefix.miss()
                        self.counters["prefix_misses"] += 1
                elif (self.queue_depth is None
                      or waiting < self.queue_depth):
                    waiting += 1
                    still.append(req)
                else:
                    # the wait queue is at depth: this arrival is
                    # rejected NOW, not queued unboundedly
                    self._terminal(req, "REJECTED_QUEUE_FULL")
                    terminal.append(req.rid)
            self.queue = still
            if admitted:
                # zero exactly the admitted slots' cache rows; one
                # compiled program regardless of WHICH slots reset —
                # the draft pool's rows reset in lockstep (same mask)
                self.exe.run(self.reset_prog, feed={"slot_keep": keep},
                             fetch_list=[])
                if self.spec_k:
                    self.exe.run(self.draft_reset,
                                 feed={"slot_keep": keep}, fetch_list=[],
                                 scope=self.draft_scope)
                if loads:
                    # prefix hits: copy the matched KV rows into the
                    # freshly reset slots (target + draft banks), so
                    # build_feed starts prefill AT the match boundary
                    self.prefix.load(self.exe, loads)
        active = self.pool.active_slots()
        if not active:
            self.now += 1
            return terminal
        feed, plan = self.pool.build_feed(self.hp.n_ctx)
        self.counters["prefill_chunks"] += sum(
            1 for _, s in active if s.state == PREFILL)
        prefilling = self.pool.any_prefilling()
        phase = "prefill" if prefilling else "decode"
        self.counters[phase + "_steps"] += 1
        # speculative round: draft k_s tokens per decoding slot through
        # the draft pool's ragged program (dispatch #1 rides the as-built
        # feed, so prompt chunks prefill the draft cache in lockstep),
        # then WIDEN the spec rows of the one target dispatch to
        # anchor+drafts verify chunks — feed VALUES change, shapes never
        spec, drafts, daux, draft_due = [], {}, {}, None
        if self.spec_k:
            spec, drafts, daux, draft_due = self._draft_round(feed, plan)
            for slot, k_s in spec:
                s = self.pool.slots[slot]
                feed["step_ids"][slot, 1:1 + k_s] = drafts[slot]
                feed["width_rows"][slot] = 1 + k_s
        with RecordEvent("serve_step", cat=phase):
            (logits,) = self.exe.run(self.step_main, feed=feed,
                                     fetch_list=self.step_fetch)
        logits = np.asarray(logits)
        finished = []
        with RecordEvent("serve_sample", cat="sample"):
            # slots whose chunk did not finish a prompt just advance
            due = {slot for slot, _ in plan}
            for slot, s in active:
                if slot not in due:
                    self.pool.advance_prefill(slot)
            spec_set = {slot for slot, _ in spec}
            plain = [(slot, col) for slot, col in plan
                     if slot not in spec_set]
            if plain:
                rows = np.stack([logits[slot, col] for slot, col in plain])
                drows = (np.stack([draft_due[slot] for slot, _ in plain])
                         if draft_due is not None else None)
                toks = self._pick_tokens(rows, [s for s, _ in plain],
                                         draft_rows=drows)
                for (slot, _), tok in zip(plain, toks):
                    s = self.pool.slots[slot]
                    done = self.pool.advance(slot, tok)
                    self.counters["new_tokens"] += 1
                    if done:
                        self._finish(slot)
                        finished.append(s.req.rid)
            for slot, k_s in spec:
                s = self.pool.slots[slot]
                emit, accepted = self._resolve_spec_row(
                    s, logits[slot], drafts[slot], daux.get(slot), k_s)
                self.counters["spec_rounds"] += 1
                self.counters["spec_proposed"] += k_s
                self.counters["spec_accepted"] += accepted
                s.spec_proposed += k_s
                s.spec_accepted += accepted
                for tok in emit:
                    done = self.pool.advance(slot, tok)
                    self.counters["new_tokens"] += 1
                    if done:
                        # budget/EOS mid-round: later tokens discarded
                        # (a solo run would never have emitted them)
                        self._finish(slot)
                        finished.append(s.req.rid)
                        break
        self.counters["steps"] += 1
        self.counters["occupancy_sum"] += len(active) / self.n_slots
        self.now += 1
        return terminal + finished

    # ---- speculative round machinery -----------------------------------
    def _spec_eligible(self, slot, s):
        """How many tokens the draft may propose for this slot THIS
        round: spec_k-1, capped by the remaining budget (a round emits
        at most k_s+1 tokens; never draft past the budget) and by cache
        capacity (the verify chunk writes anchor+drafts at
        pos..pos+k_s — the tail falls back to plain one-token decode,
        the solo core's capacity-tail rule)."""
        if s.state != DECODE:
            return 0
        remaining = s.req.max_new_tokens - len(s.out)
        return max(0, min(self.spec_k - 1, remaining - 1,
                          self.t_max - s.pos - 1))

    def _run_draft(self, feed):
        self.counters["draft_steps"] += 1
        dfeed = dict(feed)
        # the draft's position table may be shorter than the target's;
        # clip the (never-read) out-of-width columns into it
        dfeed["pos_mat"] = np.minimum(feed["pos_mat"],
                                      self.draft_hp.n_ctx - 1)
        (dl,) = self.exe.run(self.draft_main, feed=dfeed,
                             fetch_list=self.draft_fetch,
                             scope=self.draft_scope)
        return np.asarray(dl)

    def _draft_pick(self, s, pd_row, token_index):
        """One draft proposal: greedy rows argmax; sampled rows draw
        from the FILTERED draft row with the keyed DRAFT stream at the
        global token index — re-derivable by the resolver and by any
        replay (pure function of seed + index + prefix)."""
        from ..models.decode_cache import spec_propose_keyed

        if s.req.greedy:
            return int(pd_row.argmax())
        return spec_propose_keyed(pd_row, s.req.seed, token_index)

    def _filtered_row(self, s, logits_row):
        from ..models.decode_cache import filtered_probs_rows

        return filtered_probs_rows(
            np.asarray(logits_row)[None, :], [s.req.temperature],
            [s.req.top_k], [s.req.top_p])[0]

    def _draft_round(self, feed, plan):
        """The per-step draft phase.  Dispatch #1 runs the AS-BUILT
        pooled feed through the draft program — prompt chunks prefill
        the draft cache in lockstep with the target's, and every
        decoding slot's anchor keeps the draft cache position-current
        (free: one dispatch covers all rows).  Spec-eligible slots then
        draft k_s-1 more tokens one dispatch at a time (dispatch count =
        max k_s, values-only feeds — zero retraces).  Returns
        (spec rows [(slot, k_s)], drafts {slot: [token]},
        daux {slot: [filtered draft rows]} for sampled slots,
        draft_due {slot: raw draft logits row} for the due plan rows —
        the unified keyed rule needs the draft distribution even on
        plain-decode and prefill-finish rows)."""
        active = self.pool.active_slots()
        spec = [(slot, k) for slot, s in active
                for k in (self._spec_eligible(slot, s),) if k >= 1]
        dl = self._run_draft(feed)
        draft_due = {slot: dl[slot, col] for slot, col in plan}
        drafts, daux = {}, {}
        live = []
        for slot, k_s in spec:
            s = self.pool.slots[slot]
            base = (len(s.out)
                    + getattr(s.req, "sample_step_base", 0))
            pd = (None if s.req.greedy
                  else self._filtered_row(s, dl[slot, 0]))
            raw = dl[slot, 0] if s.req.greedy else pd
            tok = self._draft_pick(s, raw, base)
            drafts[slot] = [tok]
            daux[slot] = [pd]
            live.append((slot, k_s, base))
        max_k = max((k for _, k in spec), default=0)
        b, w = self.n_slots, self.width
        for j in range(1, max_k):
            ids = np.zeros((b, w), "int64")
            pos_rows = np.zeros(b, "int64")
            width_rows = np.zeros(b, "int64")
            rows = [(slot, k_s, base) for slot, k_s, base in live
                    if k_s > j]
            if not rows:
                break
            for slot, k_s, base in rows:
                s = self.pool.slots[slot]
                ids[slot, 0] = drafts[slot][-1]
                pos_rows[slot] = s.pos + j
                width_rows[slot] = 1
            pos_mat = np.clip(
                pos_rows[:, None] + np.arange(w, dtype="int64")[None, :],
                0, self.hp.n_ctx - 1)
            dl = self._run_draft({"step_ids": ids, "pos_rows": pos_rows,
                                  "width_rows": width_rows,
                                  "pos_mat": pos_mat})
            for slot, k_s, base in rows:
                s = self.pool.slots[slot]
                pd = (None if s.req.greedy
                      else self._filtered_row(s, dl[slot, 0]))
                raw = dl[slot, 0] if s.req.greedy else pd
                tok = self._draft_pick(s, raw, base + j)
                drafts[slot].append(tok)
                daux[slot].append(pd)
        return spec, drafts, daux, draft_due

    def _resolve_spec_row(self, s, logits_row, d_list, pd_list, k_s):
        """Resolve one slot's verify chunk: logits_row [W, vocab] from
        the widened target dispatch, columns 0..k_s scoring
        anchor+drafts.  Greedy: the SOLO resolver rule
        (decode_cache.greedy_accept_len) — longest draft==argmax prefix
        plus the bonus/correction column, bit-identical to the
        non-speculative argmax chain.  Sampled: per-index keyed
        rejection sampling (decode_cache.spec_accept_keyed) — accepted
        tokens ARE the emitted prefix, the first rejection emits the
        residual draw and stops; NO bonus on full acceptance (a bonus
        has no draft proposal, so it would make the emitted token at
        that index depend on round structure and break replay/solo
        equality).  Rollback is free: pool.advance only moves `pos`
        over EMITTED tokens — rejected drafts' K/V sit beyond it,
        masked (<= pos) until overwritten by the next round's writes.
        Returns (emit list, accepted draft count)."""
        from ..models.decode_cache import (greedy_accept_len,
                                           spec_accept_keyed)

        r = s.req
        if r.greedy:
            tgt_next = np.asarray(logits_row[:k_s + 1]).argmax(-1)
            tgt_next = tgt_next.astype("int64")[None, :]
            j = greedy_accept_len(
                tgt_next, [np.asarray([d], "int64") for d in d_list])
            return d_list[:j] + [int(tgt_next[0, j])], j
        base = len(s.out) + getattr(r, "sample_step_base", 0)
        emit, accepted = [], 0
        for jj in range(k_s):
            pt = self._filtered_row(s, logits_row[jj])
            tok, ok = spec_accept_keyed(
                d_list[jj], pt, pd_list[jj], r.seed, base + jj)
            emit.append(tok)
            if not ok:
                break
            accepted += 1
        return emit, accepted

    def _pick_tokens(self, rows, slots, draft_rows=None):
        """Per-row token selection with PER-REQUEST params, VECTORIZED
        over the due rows (PR 9's documented "loops per row" limit
        closed): greedy rows argmax in one batched pass, sampled rows
        run ONE batched filtered_probs_rows (itself vectorized, bit-
        identical to the per-row chain) and draw with
        fold_in(seed, request_step) keys — a pure function of
        (request, step), neighbors invisible.

        draft_rows (speculative engines only): the matching raw DRAFT
        logits rows.  Sampled rows then emit via the per-index keyed
        propose/accept/residual rule instead of the plain keyed draw —
        the SAME rule the in-round resolver applies, so a request's
        token at index t is one pure function of (seed, t, prefix)
        whether it was emitted by a verify round, the first-token
        prefill path, or a capacity-tail plain step."""
        from ..models.decode_cache import (
            filtered_probs_rows,
            sample_rows_keyed,
            spec_token_keyed,
        )

        rows = np.asarray(rows)
        sl = [self.pool.slots[s] for s in slots]
        greedy = np.array([s.req.greedy for s in sl], bool)
        out = np.zeros(len(slots), "int64")
        if greedy.any():
            out[greedy] = rows[greedy].argmax(axis=-1)
        samp = np.nonzero(~greedy)[0]
        if samp.size:
            ss = [sl[j] for j in samp]
            probs = filtered_probs_rows(
                rows[samp],
                [s.req.temperature for s in ss],
                [s.req.top_k for s in ss],
                [s.req.top_p for s in ss])
            # request_step = GLOBAL token index: a failover-replayed
            # request (router) carries the dead pool's emitted
            # prefix inside its prompt and offsets the key base past
            # it, so the continuation draws the solo run's tokens
            steps = [len(s.out) + getattr(s.req, "sample_step_base", 0)
                     for s in ss]
            if draft_rows is None:
                out[samp] = sample_rows_keyed(
                    probs, [s.req.seed for s in ss], steps)
            else:
                pds = filtered_probs_rows(
                    np.asarray(draft_rows)[samp],
                    [s.req.temperature for s in ss],
                    [s.req.top_k for s in ss],
                    [s.req.top_p for s in ss])
                for i, s in enumerate(ss):
                    tok, ok = spec_token_keyed(
                        probs[i], pds[i], s.req.seed, steps[i])
                    out[samp[i]] = tok
                    self.counters["spec_proposed"] += 1
                    self.counters["spec_accepted"] += int(ok)
                    s.spec_proposed += 1
                    s.spec_accepted += int(ok)
        return out

    def _finish(self, slot):
        s = self.pool.evict(slot)
        self.counters["finished"] += 1
        wall = time.time()
        a = min(s.req.arrival_step, max(0, len(self._step_wall) - 1))
        self._results[s.req.rid] = {
            "tokens": np.asarray(s.out, "int64"),
            "prompt_len": int(s.req.prompt.size),
            "arrival_step": s.req.arrival_step,
            "admit_step": s.admit_step,
            "finish_step": self.now,
            "status": "OK",
            "latency_steps": self.now - s.req.arrival_step + 1,
            "latency_s": wall - (self._step_wall[a] if self._step_wall
                                 else wall),
            "prefix_len": s.prefix_len,
            "spec_proposed": s.spec_proposed,
            "spec_accepted": s.spec_accepted,
            "accept_rate": _accept_rate(s.spec_accepted, s.spec_proposed),
        }

    # ---- result serialization (out-of-process pools) -------------------
    @staticmethod
    def wire_result(r):
        """One terminal result coerced onto the RPC wire's closed type
        system: tokens stay an int64 ndarray (wire-native), every scalar
        is forced to a plain int/float/str/None — a stray np.int64
        leaking into finish_step would fail the codec, and the statuses
        (OK / DEADLINE_EXPIRED / REJECTED_QUEUE_FULL) must cross the
        wire unchanged for the router's backpressure accounting."""

        def _scalar(v):
            if v is None or isinstance(v, (str, bool)):
                return v
            if isinstance(v, (int, np.integer)):
                return int(v)
            return float(v)

        out = {}
        for k, v in r.items():
            if k == "tokens":
                out[k] = np.asarray(v, "int64")
            else:
                out[k] = _scalar(v)
        return out

    def wire_results(self, rids=None):
        """Terminal results for `rids` (default: all) as wire-safe
        dicts, each tagged with its "rid" — the pool worker's `step` /
        `results` reply payload."""
        keys = self._results.keys() if rids is None else rids
        out = []
        for rid in keys:
            r = self.wire_result(self._results[rid])
            r["rid"] = rid
            out.append(r)
        return out

    # ---- pool placement accounting -------------------------------------
    def kv_pool_bytes(self, scope=None):
        """Where the KV slot-pool actually lives: total pool bytes, the
        per-device resident bytes (dedup'd by shard index, so a
        replicated pool reports its full size on EVERY device), and
        their max — the tensor-parallel acceptance number is
        max_device_bytes / total_bytes ~ 1/N on the heads axis.  Call
        after a run (the caches must exist in the scope)."""
        import jax

        from ..core.scope import global_scope

        scope = scope or global_scope()
        total = 0
        per_dev = {}
        for n in self.cache_names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    "kv_pool_bytes: cache %r not in scope — run the "
                    "engine (or its cache startup) first" % n)
            arr = v if isinstance(v, jax.Array) else np.asarray(v)
            nbytes = int(np.prod(arr.shape)) * arr.dtype.itemsize
            total += nbytes
            shards = getattr(arr, "addressable_shards", None)
            if not shards:
                per_dev["host"] = per_dev.get("host", 0) + nbytes
                continue
            for s in shards:
                d = str(s.device)
                per_dev[d] = per_dev.get(d, 0) + int(s.data.size
                                                     * s.data.itemsize)
        return {
            "total_bytes": total,
            "per_device_bytes": per_dev,
            "max_device_bytes": max(per_dev.values()) if per_dev else 0,
        }

    # ---- prefix registration -------------------------------------------
    def register_prefix(self, tokens):
        """Make the KV of a common prompt prefix resident: prefill slot
        0 with `tokens` (chunk-floored) through the REAL step program(s)
        — target and, on a speculative engine, the draft, so both banks
        hold exactly the bytes a cold prefill would have produced — then
        copy slot 0's rows into a prefix-pool row via the compiled store
        program.  The engine must be idle (registration borrows slot 0).
        Returns the prefix row, or None when tokens are shorter than one
        chunk.  Re-registering identical tokens dedups to the resident
        row without re-prefilling."""
        if self.prefix is None:
            raise RuntimeError("engine built without prefix_rows")
        if self.queue or self.pool.active_slots():
            raise RuntimeError("register_prefix on a busy engine")
        tokens = np.asarray(tokens, "int64").reshape(-1)
        ln = (int(tokens.size) // self.prefix_chunk) * self.prefix_chunk
        if ln < self.prefix_chunk:
            return None
        tokens = tokens[:ln]
        row, fresh = self.prefix.assign(tokens)
        if not fresh:
            return row
        # full cache startups (not the per-slot reset): the engine is
        # idle, and registration may precede the first run() — the slot
        # pools must exist in scope before the copy programs touch them
        self.exe.run(self.cache_startup)
        if self.spec_k:
            self.exe.run(self.draft_startup, scope=self.draft_scope)
        b, w = self.n_slots, self.width
        for c0 in range(0, ln, w):
            chunk = tokens[c0:c0 + w]
            ids = np.zeros((b, w), "int64")
            ids[0, :chunk.size] = chunk
            pos_rows = np.zeros(b, "int64")
            pos_rows[0] = c0
            width_rows = np.zeros(b, "int64")
            width_rows[0] = chunk.size
            pos_mat = np.clip(
                pos_rows[:, None] + np.arange(w, dtype="int64")[None, :],
                0, self.hp.n_ctx - 1)
            feed = {"step_ids": ids, "pos_rows": pos_rows,
                    "width_rows": width_rows, "pos_mat": pos_mat}
            # fetch the (discarded) logits so the dispatch reuses the
            # serving executable — a fetch-less variant would compile a
            # second one and break the pinned compile count
            self.exe.run(self.step_main, feed=feed,
                         fetch_list=self.step_fetch)
            if self.spec_k:
                self._run_draft(feed)
        self.prefix.store(self.exe, row, slot=0)
        return row

    def observe_prefixes(self, requests, min_count=2):
        """The "observed" registration path: find chunk-floored prompt
        prefixes SHARED by >= min_count of `requests` (grouped by first
        chunk, longest common prefix per group) and register them.
        Host-side analysis + register_prefix — run while idle, e.g.
        between serving episodes on a recent trace sample.  Returns the
        registered rows."""
        if self.prefix is None:
            raise RuntimeError("engine built without prefix_rows")
        chunk = self.prefix_chunk
        groups = {}
        for r in requests:
            p = np.asarray(r.prompt, "int64").reshape(-1)
            if p.size - 1 < chunk:
                continue
            groups.setdefault(tuple(p[:chunk].tolist()), []).append(p)
        rows = []
        for ps in groups.values():
            if len(ps) < min_count:
                continue
            base = ps[0]
            lcp = min(int(q.size) for q in ps)
            for q in ps[1:]:
                n = min(lcp, int(base.size), int(q.size))
                eq = base[:n] == q[:n]
                lcp = n if eq.all() else int(np.argmax(~eq))
            ln = (lcp // chunk) * chunk
            if ln >= chunk:
                row = self.register_prefix(base[:ln])
                if row is not None:
                    rows.append(row)
        return rows

    # ---- control-plane snapshot ----------------------------------------
    def stats(self):
        """Counters snapshot + derived rates — the shape the fabric's
        `stats` control verb and `launch.py --supervise` surface
        per pool (acceptance rate and prefix-hit counters included)."""
        c = dict(self.counters)
        c["compile_count"] = int(self.exe.compile_count)
        c["accept_rate"] = _accept_rate(c["spec_accepted"],
                                        c["spec_proposed"])
        c["spec_on"] = bool(self.spec_k)
        c["spec_k"] = int(self.spec_k)
        if self.prefix is not None:
            c["prefix_hit_rate"] = (
                c["prefix_hits"]
                / max(1, c["prefix_hits"] + c["prefix_misses"]))
            c.update(self.prefix.counters())
        return c

    # ---- episode drivers ----------------------------------------------
    def run(self, requests=None, max_steps=100000):
        """Serve `requests` (plus anything already queued) to
        completion: zero the caches, loop step() until drained.
        Returns (results, stats) — results keyed by request id with the
        emitted tokens and per-request latency, stats the aggregate
        COUNTERS-style dict (sustained tokens/s, occupancy %, step
        phase counts, mean step seconds)."""
        self.now = 0
        self._step_wall = []
        self._results = {}
        for k in self.counters:
            self.counters[k] = 0
        for r in requests or []:
            self.submit(r)
        self.exe.run(self.cache_startup)
        if self.spec_k:
            self.exe.run(self.draft_startup, scope=self.draft_scope)
        t0 = time.time()
        while self.queue or self.pool.active_slots():
            self._step_wall.append(time.time())
            self.step()
            if self.now >= max_steps:
                # drain the wedge before raising: a poisoned episode
                # must not leave slots occupied (run_solo would forever
                # see a busy engine and resubmits would look duplicate)
                n_left = len(self.queue) + len(self.pool.active_slots())
                self.queue = []
                for slot, _ in self.pool.active_slots():
                    self.pool.evict(slot)
                raise RuntimeError(
                    "serving engine exceeded max_steps=%d with %d "
                    "requests unfinished (state cleared; finished "
                    "results discarded)" % (max_steps, n_left))
        wall = time.time() - t0
        c = dict(self.counters)
        steps = max(1, c.pop("steps"))
        stats = {
            "steps": steps,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(c["new_tokens"] / wall, 1) if wall else 0.0,
            "occupancy_pct": round(100.0 * c.pop("occupancy_sum") / steps, 1),
            "step_s_mean": wall / steps,
            "compile_count": self.exe.compile_count,
            "accept_rate": _accept_rate(c["spec_accepted"],
                                        c["spec_proposed"]),
            "prefix_hit_rate": (c["prefix_hits"]
                                / max(1, c["prefix_hits"]
                                      + c["prefix_misses"])
                                if self.prefix is not None else 0.0),
        }
        stats.update(c)
        return self._results, stats

    def run_solo(self, req):
        """Serve ONE request through the same pooled program with every
        other slot free — the exactness reference and the
        serve-one-at-a-time baseline unit.  Returns (tokens, stats)."""
        if self.queue or self.pool.active_slots():
            raise RuntimeError("run_solo on a busy engine")
        from .trace import Request

        solo = Request(rid=req.rid, prompt=req.prompt,
                       max_new_tokens=req.max_new_tokens,
                       temperature=req.temperature, top_k=req.top_k,
                       top_p=req.top_p, seed=req.seed, eos_id=req.eos_id,
                       arrival=0.0)
        results, stats = self.run([solo])
        return results[req.rid]["tokens"], stats


def serve_one_at_a_time(engine, requests, arrival_step_seconds=None):
    """The A/B baseline: the same trace served sequentially, each
    request owning the whole pool (run_solo) — what serving looked like
    before the scheduler.  Throughput = total new tokens over total
    service wall time.  Latency replays the virtual arrival clock:
    arrivals map to seconds via `arrival_step_seconds` (pass the
    engine's measured mean step seconds so both systems face the same
    arrival process), each request starts at max(its arrival, the
    previous finish) and waits in the FIFO queue — the queueing delay
    continuous batching exists to remove.  Returns (results, stats)."""
    results = {}
    svc_total = 0.0
    tokens_total = 0
    step_s = float(arrival_step_seconds or 0.0)
    finish_v = 0.0
    for req in sorted(requests, key=lambda r: (r.arrival, r.rid)):
        t0 = time.time()
        tokens, _ = engine.run_solo(req)
        svc = time.time() - t0
        svc_total += svc
        tokens_total += int(tokens.size)
        arrive_v = req.arrival_step * step_s
        finish_v = max(arrive_v, finish_v) + svc
        results[req.rid] = {
            "tokens": tokens,
            "prompt_len": int(req.prompt.size),
            "latency_s": finish_v - arrive_v,
            "service_s": svc,
        }
    stats = {
        "wall_s": round(svc_total, 4),
        "tokens_per_s": (round(tokens_total / svc_total, 1)
                         if svc_total else 0.0),
        "new_tokens": tokens_total,
    }
    return results, stats
