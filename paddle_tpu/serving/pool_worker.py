"""Out-of-process serving pool worker (docs/SERVING.md §7, ROADMAP 3b):
one ServingEngine behind a VarServer, so the fabric's pools become REAL
processes — `--pool-schedule` chaos SIGKILLs an actual pid, the
supervisor's restart budget governs actual respawns, and the failover
exactness contract is exercised across a true process death.

The worker is purely REACTIVE: it admits and steps only when the
router's `step` verb says so, which is what keeps the fabric's lockstep
clock (and with it the exactness contract — a slot's schedule is a pure
function of its request and the step it was admitted) intact across the
process boundary.  Verbs:

  submit(req)       admit one wire-encoded Request into the engine
                    queue; a resent rid (the router's unacked-submit
                    resend after a lost ack) answers {"ok", "dup"}
                    instead of double-admitting.
  step(now, ack)    drop acked results, run ONE engine step at fabric
                    time `now`, and reply with every still-unacked
                    terminal result PLUS the post-step slot/queue
                    mirror the router replays failovers from.
  results(ack)      the resync half of step's reply (same payload, no
                    stepping) — a router recovering from a lost reply
                    re-pulls terminal results here.
  drain()           stop admitting new submissions (the router already
                    stopped placing; this makes the worker refuse, too).
  stats()           engine geometry + counters + compile_count — the
                    supervisor's scaling signals and the router's
                    attach-time hello (now carrying spec_k /
                    prefix_rows / prefix_chunk plus the acceptance and
                    prefix-reuse counters).
  register_prefix(tokens)
                    prefill `tokens` into the engine's prefix cache
                    (the fabric-wide router.register_prefix, one
                    pool's leg); row=None when the pool has none.
  shutdown()        conclude the serve loop (drain-and-retire's clean
                    exit; SIGKILL is the chaos path, not the API).

Errors ship as {"__error__": ...} (the pserver convention): raising in
a handler would only drop the connection and read as a worker death.
"""

import argparse
import json
import os
import sys
import threading
import time

__all__ = ["PoolWorkerService", "spawn_pool_worker", "main"]

READY_PREFIX = "POOL-WORKER READY "


class PoolWorkerService:
    """make_var_server service wrapping one ServingEngine + its scope.
    One lock serializes every verb — the engine is single-threaded by
    design, and the server's at-most-once dedup (same req_id) already
    keeps a retried `step` from double-stepping."""

    def __init__(self, engine, scope):
        self.engine = engine
        self.scope = scope
        self._lock = threading.RLock()
        self._unacked = {}   # rid -> wire result, until the router acks
        self._seen = set()   # every rid ever admitted (worker lifetime)
        self._draining = False
        self.done = threading.Event()

    def handle(self, verb, **kw):
        try:
            with self._lock:
                return self._dispatch(verb, **kw)
        except Exception as e:
            return {"__error__": "%s" % (e,)}

    # ---- verb dispatch -------------------------------------------------
    def _dispatch(self, verb, **kw):
        if verb == "submit":
            return self._h_submit(kw["req"])
        if verb == "step":
            return self._h_step(kw.get("now"), kw.get("ack"))
        if verb == "results":
            self._ack(kw.get("ack"))
            return self._payload()
        if verb == "drain":
            self._draining = True
            return self._payload()
        if verb == "stats":
            return self._stats()
        if verb == "register_prefix":
            return self._h_register_prefix(kw["tokens"])
        if verb == "shutdown":
            self.done.set()
            return {"ok": True}
        raise ValueError("unknown pool-worker verb %r" % (verb,))

    def _h_submit(self, wire_req):
        from .trace import Request

        req = Request.from_wire(wire_req)
        if req.rid in self._seen or req.rid in self._unacked:
            # the unacked-submit resend path: the FIRST submit landed
            # but its ack was lost — admitting again would double-decode
            return {"ok": True, "dup": True}
        if self._draining:
            return {"ok": False, "draining": True}
        self.engine.submit(req)  # capacity/duplicate errors -> __error__
        self._seen.add(req.rid)
        return {"ok": True}

    def _h_step(self, now, ack):
        from ..core.scope import scope_guard

        self._ack(ack)
        if now is not None:
            # the router's fabric clock is authoritative: a step RPC the
            # worker never saw (transport fault) must not leave its
            # admission/deadline clock drifting behind the fabric's
            self.engine.now = int(now)
        self.engine._step_wall.append(time.time())
        with scope_guard(self.scope):
            done = self.engine.step()
        for r in self.engine.wire_results(done):
            self._unacked[r["rid"]] = r
        return self._payload()

    def _h_register_prefix(self, tokens):
        """Prefill `tokens` into the engine's prefix cache (the router's
        fabric-wide register_prefix, one pool's leg).  A worker built
        without a prefix cache answers row=None — the fabric may be
        mixed and the router degrades that pool to cold prefill."""
        from ..core.scope import scope_guard

        if self.engine.prefix is None:
            return {"ok": True, "row": None}
        with scope_guard(self.scope):
            row = self.engine.register_prefix(tokens)
        return {"ok": True, "row": None if row is None else int(row)}

    def _ack(self, rids):
        for rid in rids or []:
            self._unacked.pop(rid, None)

    def _payload(self):
        """Step/results/drain reply: every unacked terminal result plus
        the post-step mirror (active slots with their emitted prefixes,
        unadmitted queue, free-slot count).  The mirror is what the
        router rebuilds failover replays from, so `out` must be the
        slot's TRUE emitted prefix — a stale mirror only costs re-decode
        work, a wrong one would fork the stream."""
        eng = self.engine
        return {
            "ok": True,
            "results": list(self._unacked.values()),
            "slots": [{"rid": s.req.rid, "out": [int(t) for t in s.out]}
                      for _, s in eng.pool.active_slots()],
            "queued": [q.rid for q in eng.queue],
            "free": len(eng.pool.free_slots()),
            "now": int(eng.now),
            "draining": self._draining,
            "compile_count": int(eng.exe.compile_count),
            "occupancy_sum": float(eng.counters["occupancy_sum"]),
            "steps": int(eng.counters["steps"]),
            # the fast-path counters the router mirrors into its stats
            # verb (speculative acceptance + prefix reuse per pool)
            "spec_proposed": int(eng.counters.get("spec_proposed", 0)),
            "spec_accepted": int(eng.counters.get("spec_accepted", 0)),
            "prefix_hits": int(eng.counters.get("prefix_hits", 0)),
            "prefix_misses": int(eng.counters.get("prefix_misses", 0)),
            "prefix_tokens_reused": int(
                eng.counters.get("prefix_tokens_reused", 0)),
        }

    def _stats(self):
        eng = self.engine
        s = self._payload()
        s.update({
            "pid": os.getpid(),
            "n_slots": int(eng.n_slots),
            "width": int(eng.width),
            "t_max": int(eng.t_max),
            # fast-path geometry for the router's attach-time hello:
            # prefix_chunk drives the router-side match estimate in
            # prefix-aware placement; 0 = the knob is off on this pool
            "spec_k": int(eng.spec_k),
            "prefix_rows": int(eng.prefix.rows if eng.prefix else 0),
            "prefix_chunk": int(eng.prefix_chunk),
        })
        s.update({k: (float(v) if isinstance(v, float) else int(v))
                  for k, v in eng.counters.items()})
        return s


# ---------------------------------------------------------------------------
# process entrypoint + spawn helper
# ---------------------------------------------------------------------------
def _build_engine(hp_overrides, n_slots, width, t_max, seed,
                  queue_depth=None, spec_k=0, prefix_rows=0,
                  prefix_chunk=None):
    """Tiny-to-real GPT2 engine in a fresh scope with a FIXED startup
    seed: every pool worker in one fabric must hold IDENTICAL weights
    (the failover-replay precondition), and the in-process solo
    reference in the tests rebuilds the same weights from the same
    (config, seed) pair.  spec_k > 0 arms SELF-draft speculation (the
    draft shares the target's weights, so every worker's draft is
    identical by the same precondition — a separate draft checkpoint
    would need its own seed/config shipped here); prefix_rows > 0 arms
    the prefix KV cache."""
    import paddle_tpu as fluid
    from ..models import gpt2
    from .engine import ServingEngine

    hp = type("HP", (gpt2.GPT2Config,),
              {k: (float(v) if k == "dropout" else int(v))
               for k, v in (hp_overrides or {}).items()})
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _, lm_startup, _, _ = gpt2.gpt2_logits_program(hp, seq_len=t_max)
        exe = fluid.Executor(fluid.CPUPlace())
        lm_startup.random_seed = int(seed)
        exe.run(lm_startup)
        eng = ServingEngine(exe, hp, n_slots=int(n_slots),
                            width=int(width), t_max=int(t_max),
                            queue_depth=queue_depth,
                            draft="self" if int(spec_k) else None,
                            spec_k=int(spec_k) or None,
                            prefix_rows=int(prefix_rows),
                            prefix_chunk=prefix_chunk)
        exe.run(eng.cache_startup)
        if eng.spec_k:
            exe.run(eng.draft_startup, scope=eng.draft_scope)
    return eng, scope


def main(argv=None):
    p = argparse.ArgumentParser(
        description="serving fabric pool worker (one engine, one "
                    "process, driven over RPC by FabricRouter)")
    p.add_argument("--endpoint", default="127.0.0.1:0")
    p.add_argument("--hp", default="{}",
                   help="json GPT2Config overrides (vocab_size, n_ctx, "
                        "d_model, n_layer, n_head, dropout)")
    p.add_argument("--n-slots", type=int, default=2)
    p.add_argument("--width", type=int, default=4)
    p.add_argument("--t-max", type=int, default=24)
    p.add_argument("--seed", type=int, default=7,
                   help="startup seed — identical across a fabric's "
                        "workers, or failover replay forks the stream")
    p.add_argument("--queue-depth", type=int, default=-1,
                   help="engine wait-queue bound (-1 = unbounded; the "
                        "router's fabric-wide depth is the real gate)")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative chunk width (0 = off; >0 arms "
                        "SELF-draft speculation — identical across "
                        "workers because the draft shares the target "
                        "weights)")
    p.add_argument("--prefix-rows", type=int, default=0,
                   help="prefix KV cache rows (0 = off)")
    p.add_argument("--prefix-chunk", type=int, default=-1,
                   help="prefix match granularity, a multiple of "
                        "--width (-1 = the engine default, == width)")
    args = p.parse_args(argv)

    from ..distributed.rpc import make_var_server

    eng, scope = _build_engine(
        json.loads(args.hp), args.n_slots, args.width, args.t_max,
        args.seed,
        queue_depth=None if args.queue_depth < 0 else args.queue_depth,
        spec_k=args.spec_k, prefix_rows=args.prefix_rows,
        prefix_chunk=None if args.prefix_chunk < 0 else args.prefix_chunk)
    service = PoolWorkerService(eng, scope)
    srv = make_var_server(args.endpoint, service)
    srv.start()
    # the spawner (tests, bench, launch.py's supervised children) learns
    # the bound port from this line — keep the format stable
    print("%sendpoint=%s pid=%d" % (READY_PREFIX, srv.endpoint,
                                    os.getpid()), flush=True)
    try:
        while not service.done.wait(0.2):
            pass
    finally:
        srv.shutdown()
    c = dict(eng.counters)
    c["compile_count"] = int(eng.exe.compile_count)
    print("POOL-WORKER STATS %s" % json.dumps(c, sort_keys=True),
          flush=True)
    return 0


def spawn_pool_worker(hp_overrides=None, n_slots=2, width=4, t_max=24,
                      seed=7, queue_depth=None, spec_k=0, prefix_rows=0,
                      prefix_chunk=None, timeout_s=120.0, env=None):
    """Spawn one worker subprocess and wait for its READY line.
    Returns (endpoint, proc) — the shape FabricRouter's process-mode
    pool_factory wants.  Stdout after READY drains on a daemon thread
    (echoed with a [pool-worker.<pid>] prefix) so the child never
    blocks on a full pipe."""
    import subprocess

    cmd = [sys.executable, "-m", "paddle_tpu.serving.pool_worker",
           "--hp", json.dumps(hp_overrides or {}),
           "--n-slots", str(int(n_slots)), "--width", str(int(width)),
           "--t-max", str(int(t_max)), "--seed", str(int(seed))]
    if queue_depth is not None:
        cmd += ["--queue-depth", str(int(queue_depth))]
    if spec_k:
        cmd += ["--spec-k", str(int(spec_k))]
    if prefix_rows:
        cmd += ["--prefix-rows", str(int(prefix_rows))]
    if prefix_chunk is not None:
        cmd += ["--prefix-chunk", str(int(prefix_chunk))]
    child_env = dict(os.environ if env is None else env)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=child_env)
    endpoint = None
    deadline = time.monotonic() + float(timeout_s)
    lines = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line.rstrip())
        if line.startswith(READY_PREFIX):
            for tok in line.split():
                if tok.startswith("endpoint="):
                    endpoint = tok.split("=", 1)[1]
            break
    if endpoint is None:
        proc.kill()
        proc.wait()
        raise RuntimeError(
            "pool worker never announced READY within %.0fs:\n%s"
            % (timeout_s, "\n".join(lines[-20:])))

    def _drain():
        for ln in proc.stdout:
            print("[pool-worker.%d] %s" % (proc.pid, ln.rstrip()),
                  flush=True)

    threading.Thread(target=_drain, daemon=True).start()
    return endpoint, proc


if __name__ == "__main__":
    sys.exit(main())
