"""Serving request records + the seeded Poisson arrival generator.

A Request is one user call: a prompt, a budget, per-request sampling
params (temperature / top-k / top-p / seed — seed None means greedy)
and an optional eos_id.  Arrival times are in ENGINE-STEP units (the
scheduler's virtual clock): a request becomes admittable at the first
step whose index >= arrival.  make_poisson_trace draws a reproducible
open-loop trace — exponential inter-arrivals at `rate` requests/step
over mixed prompt/output lengths — the bench/test workload shape.
"""

import numpy as np

__all__ = ["Request", "make_poisson_trace", "make_prefix_trace"]


class Request:
    """One serving request.  seed=None -> greedy decode; otherwise the
    token at request-step t draws from RandomState(fold_in_seed(seed,
    t)) — a pure function of (request, step), so the sample stream is
    identical solo or pooled (decode_cache.sample_rows_keyed)."""

    def __init__(self, rid, prompt, max_new_tokens, temperature=1.0,
                 top_k=0, top_p=1.0, seed=None, eos_id=None, arrival=0.0,
                 deadline=None, sample_step_base=0):
        self.rid = rid
        self.prompt = np.asarray(prompt, "int64").reshape(-1)
        assert self.prompt.size >= 1, (
            "empty prompt: seed generation with at least a BOS token")
        self.max_new_tokens = int(max_new_tokens)
        assert self.max_new_tokens >= 1, self.max_new_tokens
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = None if seed is None else int(seed)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.arrival = float(arrival)
        # admission control: engine steps from arrival within which the
        # request must FINISH — expiry while queued or mid-decode evicts
        # it with a terminal DEADLINE_EXPIRED status (None = no budget)
        self.deadline = None if deadline is None else int(deadline)
        assert self.deadline is None or self.deadline >= 1, deadline
        # failover replay (serving/router.py): a re-placed request's
        # prompt already CONTAINS the tokens the dead pool emitted, so
        # its sampling keys must start at the global token index, not 0
        # — fold_in(seed, base + request_step) keeps the re-decoded
        # stream on the solo run's sample sequence
        self.sample_step_base = int(sample_step_base)
        assert self.sample_step_base >= 0, sample_step_base

    @property
    def greedy(self):
        return self.seed is None

    # ---- wire codec (out-of-process pools, serving/pool_worker.py) ----
    def to_wire(self):
        """Flatten to the RPC wire's closed type system (ints / floats /
        None / ndarray) — a ProcessPool submit ships exactly this dict,
        and from_wire must rebuild a Request whose schedule AND sampling
        keys are identical, or the cross-process exactness contract
        breaks at the serialization boundary."""
        return {
            "rid": self.rid,
            "prompt": self.prompt,
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "seed": self.seed,
            "eos_id": self.eos_id,
            "arrival": self.arrival,
            "deadline": self.deadline,
            "sample_step_base": self.sample_step_base,
        }

    @classmethod
    def from_wire(cls, d):
        return cls(**d)

    @property
    def arrival_step(self):
        """First engine step at which this request is admittable."""
        import math

        return int(math.ceil(self.arrival))

    def __repr__(self):
        return ("Request(rid=%r, P=%d, new=%d, %s, arrival=%.2f)"
                % (self.rid, self.prompt.size, self.max_new_tokens,
                   "greedy" if self.greedy else "seed=%d" % self.seed,
                   self.arrival))


def make_poisson_trace(n_requests, rate, prompt_len_range, out_len_range,
                       vocab_size, seed=0, sampled_fraction=0.5,
                       eos_id=None):
    """Seeded open-loop trace: `n_requests` requests with exponential
    inter-arrival times at `rate` requests per engine step, prompt and
    output lengths uniform over the given (lo, hi) inclusive ranges,
    and a `sampled_fraction` of requests carrying heterogeneous
    per-request sampling params (the rest greedy).  Same seed -> the
    byte-identical trace, which is what makes the serve bench and the
    churn-exactness tests replayable."""
    rng = np.random.RandomState(seed)
    p_lo, p_hi = prompt_len_range
    o_lo, o_hi = out_len_range
    t = 0.0
    reqs = []
    for i in range(int(n_requests)):
        t += rng.exponential(1.0 / float(rate))
        p = int(rng.randint(p_lo, p_hi + 1))
        prompt = rng.randint(1, vocab_size, p).astype("int64")
        sampled = rng.rand() < sampled_fraction
        reqs.append(Request(
            rid=i,
            prompt=prompt,
            max_new_tokens=int(rng.randint(o_lo, o_hi + 1)),
            temperature=float(rng.uniform(0.7, 1.3)) if sampled else 1.0,
            top_k=int(rng.choice([0, 8, 32])) if sampled else 0,
            top_p=float(rng.choice([1.0, 0.9])) if sampled else 1.0,
            seed=int(rng.randint(0, 2 ** 31)) if sampled else None,
            eos_id=eos_id,
            arrival=t,
        ))
    return reqs


def make_prefix_trace(n_requests, rate, n_prefixes, prefix_len,
                      tail_len_range, out_len_range, vocab_size, seed=0,
                      reuse_fraction=0.8, sampled_fraction=0.5,
                      eos_id=None):
    """The prefix-heavy open-loop trace (ROADMAP item 4's million-user
    common case): a pool of `n_prefixes` shared TEMPLATE prefixes
    (system prompts / few-shot scaffolds) of `prefix_len` tokens each;
    every request with probability `reuse_fraction` opens with one of
    them (uniform choice) followed by a fresh random tail of
    tail_len_range tokens, else carries a fully random prompt of
    prefix_len//2 + tail tokens (cold traffic).  Arrivals, lengths and
    sampling params draw exactly like make_poisson_trace — seeded and
    deterministic, same seed -> byte-identical trace.

    Returns (requests, prefixes): register `prefixes` on the engine
    (engine.register_prefix / router.register_prefix) to arm the prefix
    cache; serving the SAME trace with and without registration is the
    bench's A/B — streams must match bit-for-bit, only the prefill
    dispatch count and tok/s move."""
    rng = np.random.RandomState(seed)
    prefix_len = int(prefix_len)
    prefixes = [rng.randint(1, vocab_size, prefix_len).astype("int64")
                for _ in range(int(n_prefixes))]
    t_lo, t_hi = tail_len_range
    o_lo, o_hi = out_len_range
    t = 0.0
    reqs = []
    for i in range(int(n_requests)):
        t += rng.exponential(1.0 / float(rate))
        tail = rng.randint(
            1, vocab_size, int(rng.randint(t_lo, t_hi + 1))).astype("int64")
        if rng.rand() < reuse_fraction:
            tmpl = prefixes[int(rng.randint(0, len(prefixes)))]
            prompt = np.concatenate([tmpl, tail])
        else:
            cold = rng.randint(
                1, vocab_size, max(1, prefix_len // 2)).astype("int64")
            prompt = np.concatenate([cold, tail])
        sampled = rng.rand() < sampled_fraction
        reqs.append(Request(
            rid=i,
            prompt=prompt,
            max_new_tokens=int(rng.randint(o_lo, o_hi + 1)),
            temperature=float(rng.uniform(0.7, 1.3)) if sampled else 1.0,
            top_k=int(rng.choice([0, 8, 32])) if sampled else 0,
            top_p=float(rng.choice([1.0, 0.9])) if sampled else 1.0,
            seed=int(rng.randint(0, 2 ** 31)) if sampled else None,
            eos_id=eos_id,
            arrival=t,
        ))
    return reqs, prefixes
