"""Slot pool for the continuous-batching engine: per-slot request
lifecycle (PREFILL -> DECODE -> done) and the per-step ragged feed.

The pool owns NO device state — the KV caches are the step program's
persistable vars; the pool only tracks which cache ROWS belong to which
request and at what position, and lays each step's work out as the
ragged step program's feed vectors (per-slot pos/width/ids).  A slot's
schedule is a pure function of its request (prompt length, budget):
prefill chunks of the program width W starting at 0, W, 2W, ... then
one-token decode — identical whether the request runs solo or shares
the pool, which is what the exactness contract leans on.
"""

import numpy as np

__all__ = ["SlotPool", "PREFILL", "DECODE"]

PREFILL = "prefill"
DECODE = "decode"


class _Slot:
    __slots__ = ("req", "state", "prefill_pos", "pos", "last_token",
                 "out", "admit_step", "prefix_len", "spec_proposed",
                 "spec_accepted")

    def __init__(self, req, admit_step, prefix_len=0):
        self.req = req
        self.state = PREFILL
        # a prefix-cache hit starts prefill AT the match boundary: the
        # first prefix_len cache rows were copied in, not dispatched
        self.prefill_pos = int(prefix_len)  # next prompt chunk starts here
        self.pos = int(prefix_len)  # tokens currently resident in the cache
        self.last_token = None   # decode input for the next step
        self.out = []            # generated tokens (int)
        self.admit_step = admit_step
        self.prefix_len = int(prefix_len)
        # per-request speculative-decoding acceptance accounting
        self.spec_proposed = 0
        self.spec_accepted = 0


class SlotPool:
    def __init__(self, n_slots, width, t_max):
        self.n_slots = int(n_slots)
        self.width = int(width)
        self.t_max = int(t_max)
        self.slots = [None] * self.n_slots

    # ---- occupancy ----------------------------------------------------
    def free_slots(self):
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self):
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    def occupancy(self):
        return sum(1 for s in self.slots if s is not None) / self.n_slots

    # ---- lifecycle ----------------------------------------------------
    def fits(self, req):
        """Non-raising capacity check: the router's cross-pool placement
        keys off this (len(prompt)+max_new vs THIS pool's t_max) when
        pools of different sizes coexist in one fabric."""
        return req.prompt.size + req.max_new_tokens <= self.t_max + 1

    def validate(self, req):
        """The pool's capacity rule (it owns t_max): the last generated
        token is never fed back, hence the +1 — the single source of
        truth for engine.submit and admit."""
        if not self.fits(req):
            raise ValueError(
                "request %r: prompt %d + new %d exceeds pool capacity %d"
                % (req.rid, req.prompt.size, req.max_new_tokens,
                   self.t_max))

    def admit(self, req, admit_step, prefix_len=0):
        """Place `req` in a free slot; returns the slot index (caller
        zero-resets that slot's cache rows before the next dispatch).
        prefix_len > 0 (a prefix-cache hit): the caller copies the
        matched KV rows in AFTER the reset, and prefill resumes at that
        boundary — it must be a multiple of the pool width and leave at
        least one prompt token to dispatch (the finishing chunk's logits
        emit the first token)."""
        free = self.free_slots()
        if not free:
            raise RuntimeError("admit with no free slot")
        self.validate(req)
        if prefix_len:
            assert prefix_len % self.width == 0, (prefix_len, self.width)
            assert 0 < prefix_len < req.prompt.size, (
                prefix_len, req.prompt.size)
        slot = free[0]
        self.slots[slot] = _Slot(req, admit_step, prefix_len=prefix_len)
        return slot

    def evict(self, slot):
        s = self.slots[slot]
        self.slots[slot] = None
        return s

    # ---- the ragged step feed -----------------------------------------
    def build_feed(self, n_ctx):
        """Lay the current occupancy out as the ragged step program's
        feed: step_ids [B, W], pos_rows/width_rows [B], pos_mat [B, W]
        (positions clipped into the position table; clipped columns are
        never written or read).  Free slots ride along as width-0 rows.
        Returns (feed dict, sample_plan) where sample_plan lists
        (slot, logits_column) for every row that must emit a token after
        this dispatch — a decoding slot's column 0, or a prefilling
        slot's final-prompt column when this chunk completes the
        prompt."""
        b, w = self.n_slots, self.width
        ids = np.zeros((b, w), "int64")
        pos_rows = np.zeros(b, "int64")
        width_rows = np.zeros(b, "int64")
        plan = []
        for i, s in self.active_slots():
            if s.state == PREFILL:
                c0 = s.prefill_pos
                chunk = s.req.prompt[c0:c0 + w]
                ids[i, :chunk.size] = chunk
                pos_rows[i] = c0
                width_rows[i] = chunk.size
                if c0 + chunk.size >= s.req.prompt.size:
                    # this chunk finishes the prompt: its last real
                    # column's logits predict position P and emit the
                    # request's first token
                    plan.append((i, s.req.prompt.size - 1 - c0))
            else:
                ids[i, 0] = s.last_token
                pos_rows[i] = s.pos
                width_rows[i] = 1
                plan.append((i, 0))
        pos_mat = np.clip(
            pos_rows[:, None] + np.arange(w, dtype="int64")[None, :],
            0, n_ctx - 1)
        feed = {"step_ids": ids, "pos_rows": pos_rows,
                "width_rows": width_rows, "pos_mat": pos_mat}
        return feed, plan

    def any_prefilling(self):
        return any(s.state == PREFILL for _, s in self.active_slots())

    # ---- post-dispatch advance ----------------------------------------
    def advance(self, slot, token):
        """Record `token` as slot's next generated token and advance its
        lifecycle.  Returns True when the request just finished (EOS or
        budget) — the caller evicts the slot."""
        s = self.slots[slot]
        r = s.req
        if s.state == PREFILL:
            # the finishing chunk wrote the remaining prompt tokens
            s.pos = r.prompt.size
            s.state = DECODE
        else:
            s.pos += 1
        s.out.append(int(token))
        s.last_token = int(token)
        if len(s.out) >= r.max_new_tokens:
            return True
        if r.eos_id is not None and int(token) == r.eos_id:
            return True
        return False

    def advance_prefill(self, slot):
        """A non-finishing prefill chunk was dispatched: move the chunk
        cursor (cache rows c0..c0+W-1 are now resident)."""
        s = self.slots[slot]
        s.prefill_pos += self.width
        s.pos = s.prefill_pos
