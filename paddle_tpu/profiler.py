"""Profiler (python/paddle/fluid/profiler.py + platform/profiler.{h,cc}
analog).

The reference wraps every op run in RecordEvent scopes and correlates CUPTI
device activity into a chrome-trace timeline (tools/timeline.py).  Here host
scopes are kept (RecordEvent spans around executor runs + user ranges) and
device-side tracing delegates to jax.profiler (XLA/xplane — TensorBoard
readable), with the host spans additionally dumped as chrome-trace JSON so
`profiler(state)`-style workflows keep their artifact.
"""

import contextlib
import json
import os
import threading
import time

__all__ = [
    "RecordEvent",
    "record_event",
    "profiler",
    "start_profiler",
    "stop_profiler",
    "reset_profiler",
    "cuda_profiler",
    "tpu_profiler",
    "per_op_timeline",
    "comm_compute_split",
    "COMM_OPS",
    "PHASE_CATS",
]

_events = []
_events_lock = threading.Lock()
_enabled = False
_trace_dir = None

# op types whose host time is DCN communication, not compute — the
# per_op_timeline comm/compute split (RPC sends/recvs/barriers plus the
# bucketed/pipelined variants and the sparse-table verbs)
COMM_OPS = frozenset((
    "send", "recv", "send_bucket", "recv_bucket", "send_barrier",
    "fetch_barrier", "prefetch", "send_sparse", "checkpoint_notify",
))


class RecordEvent:
    """RAII span (platform/profiler.h:73 RecordEvent parity).  `cat`
    categorizes the span for comm-vs-compute attribution in the chrome
    trace ("comm" for RPC sends/recvs, "feed" for host->device uploads;
    unset spans are compute/host work)."""

    def __init__(self, name, cat=None):
        self.name = name
        self.cat = cat
        self.t0 = None

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        if _enabled:
            t1 = time.time()
            ev = {
                "name": self.name,
                "ph": "X",
                "ts": self.t0 * 1e6,
                "dur": (t1 - self.t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 10000,
            }
            if self.cat:
                ev["cat"] = self.cat
            with _events_lock:
                _events.append(ev)
        return False


@contextlib.contextmanager
def record_event(name, cat=None):
    with RecordEvent(name, cat=cat):
        yield


def reset_profiler():
    with _events_lock:
        _events.clear()


def start_profiler(state="All", trace_dir=None):
    """state in {CPU, GPU/TPU, All} (API parity; device tracing is xplane)."""
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if state in ("GPU", "TPU", "All") and trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop; write host spans as chrome trace json + stop device trace."""
    global _enabled
    _enabled = False
    if _trace_dir:
        import jax

        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass
    with _events_lock:
        evs = list(_events)
    if profile_path:
        with open(profile_path + ".json" if not profile_path.endswith(".json") else profile_path, "w") as f:
            json.dump({"traceEvents": evs}, f)
    # aggregate table (EnableProfiler report parity)
    agg = {}
    for e in evs:
        a = agg.setdefault(e["name"], [0, 0.0])
        a[0] += 1
        a[1] += e["dur"] / 1e3
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
    if rows:
        print("%-40s %8s %12s" % ("Event", "Calls", "Total(ms)"))
        for name, (calls, total) in rows[:30]:
            print("%-40s %8d %12.2f" % (name[:40], calls, total))
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile", trace_dir=None):
    """`with profiler('All'):` context (fluid.profiler.profiler :221 parity)."""
    reset_profiler()
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def per_op_timeline(program, feed, scope=None, path=None, warmup=1,
                    block_idx=0):
    """Per-op host/device correlated timeline (device_tracer.h:26,49 +
    tools/timeline.py:160 capability, re-expressed for a compile-first
    engine).

    The compiled path fuses the whole block into one XLA executable, so
    per-op device attribution needs a diagnostic interpretation pass: each
    op's lowering runs eagerly on concrete arrays, timed twice — cold
    (host dispatch + compile + device) and warm (device-dominated re-run
    under block_until_ready).  Both spans share a correlation id per op
    (the reference's CUPTI correlation contract) and land in ONE
    chrome-trace JSON with separate host/device tracks.  Returns the rows
    [(op_type, idx, host_ms, device_ms)] sorted by device time.

    Flat blocks only (while/cond sub-blocks time as their parent op would
    under the real executor — use the aggregate profiler for those).
    """
    import jax
    import numpy as np

    from .core.registry import OPS, LowerCtx, get_op, lower_grad_op
    from .core.scope import global_scope
    from .core.selected_rows import SelectedRows, densify_maybe

    scope = scope or global_scope()
    blk = program.block(block_idx)
    env = {}
    for k, v in (feed or {}).items():
        env[k] = jax.numpy.asarray(np.asarray(v))
    ctx = LowerCtx(rng_key=jax.random.PRNGKey(0), scope=scope)
    events = []
    rows = []
    t_base = time.time()

    for idx, op in enumerate(blk.ops):
        if op.type in ("feed", "fetch", "read", "create_py_reader"):
            continue
        if op.type in ("while", "cond"):
            raise ValueError(
                "per_op_timeline supports flat blocks; '%s' at op %d owns "
                "a sub-block" % (op.type, idx))
        ctx.op_idx = idx
        ctx.block = blk
        opdef = OPS.get(op.type)
        ins = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if n in env:
                    vals.append(env[n])
                elif scope.has_var(n):
                    vals.append(jax.numpy.asarray(scope.find_var(n)))
                else:
                    raise RuntimeError(
                        "per_op_timeline: op %s reads %s which is neither "
                        "fed nor in scope" % (op.type, n))
            ins[slot] = vals
        # mirror the executor's SelectedRows contract: non-aware ops see
        # the densified tensor
        if any(isinstance(v, SelectedRows)
               for vs in ins.values() for v in vs) and not (
                   opdef is not None and opdef.handles_selected_rows):
            ins = {s_: [densify_maybe(v) for v in vs]
                   for s_, vs in ins.items()}

        def run_once():
            if op.type.endswith("_grad") and "__fwd_type__" in op.attrs \
                    and op.type not in OPS:
                out = lower_grad_op(ctx, op, ins, op.attrs)
            else:
                out = get_op(op.type).lower(ctx, ins, op.attrs)
            jax.block_until_ready(
                [v for vs in out.values() for v in vs if v is not None])
            return out

        t0 = time.time()
        outs = run_once()
        host_ms = (time.time() - t0) * 1e3
        dev_ms = host_ms
        # side-effect ops (RPC sends, barriers, checkpoint notifies) must
        # run exactly once — a warm re-run would duplicate the effect
        if warmup and not (opdef is not None and opdef.side_effect):
            t0 = time.time()
            for _ in range(warmup):
                outs = run_once()
            dev_ms = (time.time() - t0) * 1e3 / warmup
        ts = (time.time() - t_base) * 1e6
        cat = "comm" if op.type in COMM_OPS else "compute"
        for tid, name, dur in ((1, "host", host_ms), (2, "device", dev_ms)):
            events.append({
                "name": "%s#%d" % (op.type, idx), "ph": "X", "cat": cat,
                "ts": ts, "dur": dur * 1e3, "pid": os.getpid(), "tid": tid,
                "args": {"correlation": idx, "track": name},
            })
        rows.append((op.type, idx, host_ms, dev_ms))
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            for n, v in zip(names, vals):
                if n and v is not None:
                    env[n] = v

    if path:
        meta = [
            {"ph": "M", "pid": os.getpid(), "tid": 1, "name": "thread_name",
             "args": {"name": "host (dispatch+compile)"}},
            {"ph": "M", "pid": os.getpid(), "tid": 2, "name": "thread_name",
             "args": {"name": "device (warm re-run)"}},
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": meta + events}, f)
    return sorted(rows, key=lambda r: -r[3])


# RecordEvent categories that refine the comm bucket: wire
# serialization (rpc._send_msg), grad compression (dist_ops
# wire_compress) and the pserver's fused optimize apply
# (ps_server._run_round).  Spans with these cats are attributed to
# their own phase by comm_compute_split instead of lumping into comm.
# The serving engine's loop phases (serving/engine.py) ride the same
# mechanism: admit (admission + slot reset), prefill / decode (the
# pooled model dispatch, tagged by whether any slot is prefilling),
# sample (host-side per-request token selection) — so
# comm_compute_split(events=...) shows where serve time goes.
PHASE_CATS = ("serialize", "compress", "apply",
              "admit", "prefill", "decode", "sample")


def comm_compute_split(rows, events=None):
    """Attribute per_op_timeline rows to DCN communication vs compute:
    returns {"comm_ms", "compute_ms", "comm_fraction"} over the host
    track — where the step's wall time actually goes when deciding
    whether bucketing/overlap or kernels are the bottleneck.

    When cat-tagged phase spans were recorded (`events`; defaults to the
    profiler's captured span list), the split additionally reports
    serialize/compress/apply milliseconds — the wire-compression and
    fused-apply phases — so those show up as their own lines instead of
    disappearing into comm."""
    comm = sum(r[2] for r in rows if r[0] in COMM_OPS)
    compute = sum(r[2] for r in rows if r[0] not in COMM_OPS)
    total = comm + compute
    out = {
        "comm_ms": round(comm, 3),
        "compute_ms": round(compute, 3),
        "comm_fraction": round(comm / total, 4) if total else 0.0,
    }
    if events is None:
        with _events_lock:
            events = list(_events)
    for cat in PHASE_CATS:
        ms = sum(e["dur"] for e in events if e.get("cat") == cat) / 1e3
        if ms:
            out[cat + "_ms"] = round(ms, 3)
    return out


@contextlib.contextmanager
def tpu_profiler(output_dir):
    """Device-side trace via jax.profiler (cuda_profiler :39 analog)."""
    import jax

    jax.profiler.start_trace(output_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


cuda_profiler = tpu_profiler  # API alias for reference scripts
