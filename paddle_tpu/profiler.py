"""Profiler (python/paddle/fluid/profiler.py + platform/profiler.{h,cc}
analog).

The reference wraps every op run in RecordEvent scopes and correlates CUPTI
device activity into a chrome-trace timeline (tools/timeline.py).  Here host
scopes are kept (RecordEvent spans around executor runs + user ranges) and
device-side tracing delegates to jax.profiler (XLA/xplane — TensorBoard
readable), with the host spans additionally dumped as chrome-trace JSON so
`profiler(state)`-style workflows keep their artifact.
"""

import contextlib
import json
import os
import threading
import time

__all__ = [
    "RecordEvent",
    "record_event",
    "profiler",
    "start_profiler",
    "stop_profiler",
    "reset_profiler",
    "cuda_profiler",
    "tpu_profiler",
]

_events = []
_events_lock = threading.Lock()
_enabled = False
_trace_dir = None


class RecordEvent:
    """RAII span (platform/profiler.h:73 RecordEvent parity)."""

    def __init__(self, name):
        self.name = name
        self.t0 = None

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        if _enabled:
            t1 = time.time()
            with _events_lock:
                _events.append(
                    {
                        "name": self.name,
                        "ph": "X",
                        "ts": self.t0 * 1e6,
                        "dur": (t1 - self.t0) * 1e6,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() % 10000,
                    }
                )
        return False


@contextlib.contextmanager
def record_event(name):
    with RecordEvent(name):
        yield


def reset_profiler():
    with _events_lock:
        _events.clear()


def start_profiler(state="All", trace_dir=None):
    """state in {CPU, GPU/TPU, All} (API parity; device tracing is xplane)."""
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if state in ("GPU", "TPU", "All") and trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop; write host spans as chrome trace json + stop device trace."""
    global _enabled
    _enabled = False
    if _trace_dir:
        import jax

        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass
    with _events_lock:
        evs = list(_events)
    if profile_path:
        with open(profile_path + ".json" if not profile_path.endswith(".json") else profile_path, "w") as f:
            json.dump({"traceEvents": evs}, f)
    # aggregate table (EnableProfiler report parity)
    agg = {}
    for e in evs:
        a = agg.setdefault(e["name"], [0, 0.0])
        a[0] += 1
        a[1] += e["dur"] / 1e3
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
    if rows:
        print("%-40s %8s %12s" % ("Event", "Calls", "Total(ms)"))
        for name, (calls, total) in rows[:30]:
            print("%-40s %8d %12.2f" % (name[:40], calls, total))
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile", trace_dir=None):
    """`with profiler('All'):` context (fluid.profiler.profiler :221 parity)."""
    reset_profiler()
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def tpu_profiler(output_dir):
    """Device-side trace via jax.profiler (cuda_profiler :39 analog)."""
    import jax

    jax.profiler.start_trace(output_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


cuda_profiler = tpu_profiler  # API alias for reference scripts
