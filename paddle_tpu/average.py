"""WeightedAverage accumulator (python/paddle/fluid/average.py parity)."""

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(var):
    return isinstance(var, (int, float, np.ndarray)) or np.isscalar(var)


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError("add() takes a number or numpy array")
        if not _is_number_or_matrix(weight):
            raise ValueError("weight must be a number or numpy array")
        if self.numerator is None or self.denominator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator is None:
            raise ValueError("eval() before any add()")
        return self.numerator / self.denominator
