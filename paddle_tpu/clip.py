"""Gradient clipping (python/paddle/fluid/clip.py analog): ByValue/ByNorm/
ByGlobalNorm (clip.py:120,166,212) emitted as ops on gradients."""

from . import framework, layers

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "append_gradient_clip_ops",
]

_clip_attr = None


class BaseGradientClipAttr:
    def _process(self, param, grad):
        raise NotImplementedError


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _process(self, param, grad):
        return param, layers.clip(grad, self.min, self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, param, grad):
        return param, layers.clip_by_norm(grad, self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_group(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None:
                continue
            sq.append(layers.reduce_sum(layers.square(g)))
        global_norm = layers.sqrt(layers.sums(sq))
        clip_val = layers.fill_constant([1], "float32", self.clip_norm)
        scale = clip_val / layers.elementwise_max(global_norm, clip_val)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.elementwise_mul(g, scale)))
        return out


def set_gradient_clip(clip, param_list=None, program=None):
    global _clip_attr
    if param_list is not None:
        program = program or framework.default_main_program()
        for p in param_list:
            if isinstance(p, str):
                p = program.global_block().var(p)
            p.gradient_clip_attr = clip
    else:
        _clip_attr = clip


def append_gradient_clip_ops(params_grads):
    global _clip_attr
    if _clip_attr is None and not any(
        p.gradient_clip_attr is not None for p, g in params_grads
    ):
        return params_grads
    out = []
    # global-norm clips are grouped (per group_name) so the norm spans the
    # whole parameter group, as in the reference's clip.py:212
    groups = {}
    for p, g in params_grads:
        clip = p.gradient_clip_attr or _clip_attr
        if g is None or clip is None:
            out.append((p, g))
        elif isinstance(clip, GradientClipByGlobalNorm):
            groups.setdefault(clip.group_name, (clip, []))[1].append((p, g))
        else:
            out.append(clip._process(p, g))
    for clip, pgs in groups.values():
        out.extend(clip._process_group(pgs))
    return out
