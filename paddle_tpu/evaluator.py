"""In-program evaluators (python/paddle/fluid/evaluator.py parity).

An Evaluator owns persistable state vars in the main program, appends
accumulation ops per minibatch, and reads the aggregate out of the scope
in eval().  The reference marks this module deprecated in favor of
fluid.metrics — both are provided here (metrics.py has the pure-python
accumulators; these are the program-integrated versions).
"""

import itertools
import os
import weakref

import numpy as np

from . import framework, unique_name
from .framework import Variable
from .layer_helper import LayerHelper

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """Base: create persistable zero-initialized state vars + reset()."""

    def __init__(self, name):
        self.helper = LayerHelper(name, name=name)
        self.states = []
        self.metrics = []

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.create_variable(
            name="_".join([unique_name.generate(self.helper.name), suffix]),
            persistable=True,
            dtype=dtype,
            shape=shape,
        )
        self.states.append(state)
        # zero-init in the startup program (reference resets via
        # fill_constant in reset(); initial value must exist either way)
        self.helper.set_variable_initializer(
            state, initializer=__import__(
                "paddle_tpu.initializer", fromlist=["Constant"]
            ).Constant(0.0 if dtype.startswith("float") else 0)
        )
        return state

    def reset(self, executor, reset_program=None):
        """Zero all state vars (runs a tiny fill program)."""
        if reset_program is None:
            reset_program = framework.Program()
        with framework.program_guard(reset_program):
            for var in self.states:
                blk = reset_program.global_block()
                z = blk.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                                   persistable=True)
                blk.append_op(
                    "fill_constant",
                    outputs={"Out": [z]},
                    attrs={"shape": list(var.shape or [1]),
                           "dtype": var.dtype, "value": 0.0},
                )
        executor.run(reset_program, feed={}, fetch_list=[])

    def eval(self, executor, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Accumulated chunk F1 (evaluator.py ChunkEvaluator): wraps the
    chunk_eval op and accumulates counts across minibatches."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_len=None):
        super().__init__("chunk_evaluator")
        main = framework.default_main_program()
        self.num_infer_chunks = self._create_state("num_infer", "int64", [1])
        self.num_label_chunks = self._create_state("num_label", "int64", [1])
        self.num_correct_chunks = self._create_state("num_correct", "int64", [1])
        from .layers import nn as nn_layers

        helper = self.helper
        precision = helper.create_variable_for_type_inference("float32")
        recall = helper.create_variable_for_type_inference("float32")
        f1 = helper.create_variable_for_type_inference("float32")
        ni = helper.create_variable_for_type_inference("int64")
        nl = helper.create_variable_for_type_inference("int64")
        nc = helper.create_variable_for_type_inference("int64")
        inputs = {"Inference": [input], "Label": [label]}
        if seq_len is not None:
            inputs["Length"] = [seq_len]
        helper.append_op(
            "chunk_eval",
            inputs=inputs,
            outputs={
                "Precision": [precision],
                "Recall": [recall],
                "F1-Score": [f1],
                "NumInferChunks": [ni],
                "NumLabelChunks": [nl],
                "NumCorrectChunks": [nc],
            },
            attrs={
                "chunk_scheme": chunk_scheme,
                "num_chunk_types": num_chunk_types,
                "excluded_chunk_types": excluded_chunk_types or [],
            },
        )
        # state += batch counts
        for state, batch in [
            (self.num_infer_chunks, ni),
            (self.num_label_chunks, nl),
            (self.num_correct_chunks, nc),
        ]:
            helper.append_op(
                "elementwise_add",
                inputs={"X": [state], "Y": [batch]},
                outputs={"Out": [state]},
            )
        self.metrics = [precision, recall, f1]

    def eval(self, executor, eval_program=None):
        from .core.scope import global_scope

        scope = global_scope()
        ni = float(np.asarray(scope.get(self.num_infer_chunks.name)).reshape(-1)[0])
        nl = float(np.asarray(scope.get(self.num_label_chunks.name)).reshape(-1)[0])
        nc = float(np.asarray(scope.get(self.num_correct_chunks.name)).reshape(-1)[0])
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = 2 * precision * recall / (precision + recall) if nc else 0.0
        return np.array([precision]), np.array([recall]), np.array([f1])


class EditDistance(Evaluator):
    """Accumulated average edit distance (evaluator.py EditDistance):
    wraps the edit_distance op and tracks (total distance, #errors, #seqs)."""

    def __init__(self, input, label, ignored_tokens=None, seq_len=None,
                 label_len=None):
        super().__init__("edit_distance_evaluator")
        self.total_distance = self._create_state("total_dist", "float32", [1])
        self.seq_num = self._create_state("seq_num", "int64", [1])
        self.instance_error = self._create_state("inst_err", "int64", [1])
        helper = self.helper
        dist = helper.create_variable_for_type_inference("float32")
        seq_num = helper.create_variable_for_type_inference("int64")
        inputs = {"Hyps": [input], "Refs": [label]}
        if seq_len is not None:
            inputs["HypsLength"] = [seq_len]
        if label_len is not None:
            inputs["RefsLength"] = [label_len]
        helper.append_op(
            "edit_distance",
            inputs=inputs,
            outputs={"Out": [dist], "SequenceNum": [seq_num]},
            attrs={"normalized": False},
        )
        batch_total = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "reduce_sum", inputs={"X": [dist]}, outputs={"Out": [batch_total]}
        )
        helper.append_op(
            "elementwise_add",
            inputs={"X": [self.total_distance], "Y": [batch_total]},
            outputs={"Out": [self.total_distance]},
        )
        helper.append_op(
            "elementwise_add",
            inputs={"X": [self.seq_num], "Y": [seq_num]},
            outputs={"Out": [self.seq_num]},
        )
        # instance errors = #sequences with distance > 0 (distances are
        # non-negative, so sign() is the indicator)
        sgn = helper.create_variable_for_type_inference("float32")
        helper.append_op("sign", inputs={"X": [dist]}, outputs={"Out": [sgn]})
        err = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "reduce_sum", inputs={"X": [sgn]}, outputs={"Out": [err]}
        )
        erri = helper.create_variable_for_type_inference("int64")
        helper.append_op(
            "cast", inputs={"X": [err]}, outputs={"Out": [erri]},
            attrs={"out_dtype": "int64"},
        )
        helper.append_op(
            "elementwise_add",
            inputs={"X": [self.instance_error], "Y": [erri]},
            outputs={"Out": [self.instance_error]},
        )

    def eval(self, executor, eval_program=None):
        from .core.scope import global_scope

        scope = global_scope()
        total = float(np.asarray(scope.get(self.total_distance.name)).reshape(-1)[0])
        n = float(np.asarray(scope.get(self.seq_num.name)).reshape(-1)[0])
        err = float(np.asarray(scope.get(self.instance_error.name)).reshape(-1)[0])
        avg = total / n if n else 0.0
        return np.array([avg], "float32"), np.array([err / n if n else 0.0], "float32")


_detmap_instance_counter = itertools.count()


class DetectionMAP(Evaluator):
    """Streaming detection mAP (evaluator.py:298 DetectionMAP parity).

    Appends two detection_map ops to the current main program: one
    computing the CURRENT batch's mAP and one computing the ACCUMULATED
    mAP over every batch since the last reset().  The reference threads
    Accum{PosCount,TruePos,FalsePos} LoD state tensors through the op;
    detection eval state is ragged per-class score lists, so here the
    accumulating op owns a persistent host-side accumulator behind its
    `accum_key` (sequenced with io_callback(ordered=True) — see
    ops/compat_ops.py).  Fetch BOTH metrics each run (the accumulated
    map is updated by running its op).

    input: [N, 6] detections (label, score, x1, y1, x2, y2; label < 0 =
    padding); gt_label [G, 1], gt_box [G, 4], optional gt_difficult
    [G, 1] (the reference's concat layout is rebuilt internally).
    class_num / background_label are accepted for signature parity —
    the host evaluator derives classes from the data and detections
    never carry the background label (multiclass_nms strips it).
    """

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        super().__init__("map_eval")
        from .layers import detection as _det
        from .layers import tensor as _tensor

        parts = ([gt_label, gt_difficult, gt_box]
                 if gt_difficult is not None else [gt_label, gt_box])
        parts = [_tensor.cast(p, "float32") for p in parts]
        label = _tensor.concat(parts, axis=1)
        self.cur_map = _det.detection_map(
            input, label, overlap_threshold,
            ap_version=ap_version, evaluate_difficult=evaluate_difficult)
        # key must be guard-INDEPENDENT: unique_name.guard() resets its
        # counters, so two evaluators built in separate guard scopes
        # would otherwise share (and cross-contaminate) one accumulator
        self._accum_key = "detmap_accum_%d_%d" % (
            os.getpid(), next(_detmap_instance_counter))
        self.accum_map = _det.detection_map(
            input, label, overlap_threshold,
            ap_version=ap_version, evaluate_difficult=evaluate_difficult,
            accum_key=self._accum_key)
        self.metrics = [self.cur_map, self.accum_map]
        # the PROGRAM holds a strong reference to this evaluator (keyed
        # by accum_key, so rebuilding never pins duplicates): the ops
        # stay runnable exactly as long as the program lives, so a user
        # dropping their evaluator variable mid-run cannot silently reset
        # the stream (ADVICE r5).  The finalizer below therefore fires
        # only once the program itself is collected — an evaluator built
        # per-epoch into one LONG-LIVED program keeps each old stream
        # alive with its still-runnable ops; call reset() on the old
        # evaluator (or build into a fresh program) to release the data.
        prog = self.accum_map.block.program
        if not hasattr(prog, "_detmap_keepalive"):
            prog._detmap_keepalive = {}
        prog._detmap_keepalive[self._accum_key] = self
        # free the host accumulator (full per-detection score lists) when
        # the evaluator (with its program) is collected — rebuilt-per-
        # epoch evaluators must not leak every past epoch's stream.  The
        # finalize variant flags the key so any orphaned program copy
        # still running the op warns instead of restarting silently.
        from .ops.compat_ops import finalize_detection_map_accum

        self._finalizer = weakref.finalize(
            self, finalize_detection_map_accum, self._accum_key)

    def get_map_var(self):
        """Reference API: returns (cur_map, accum_map) variables."""
        return self.cur_map, self.accum_map

    def reset(self, executor=None, reset_program=None):
        """Clear the streaming accumulator (host state — no program)."""
        from .ops.compat_ops import reset_detection_map_accum

        reset_detection_map_accum(self._accum_key)
