"""DataFeeder (python/paddle/fluid/data_feeder.py:83 analog): convert python
minibatch rows into the feed dict of dense arrays (+ padded LoDTensors for
ragged slots)."""

import numpy as np

from . import framework
from .lod import create_lod_tensor

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = framework.default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(each_var.dtype)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, name in enumerate(self.feed_names):
            col = [row[i] for row in rows]
            if self.feed_lod_level[i] > 0:
                out[name] = create_lod_tensor([np.asarray(c) for c in col])
            else:
                shape = self.feed_shapes[i]
                arr = np.asarray(col, dtype=self.feed_dtypes[i])
                if shape is not None:
                    feat = [d for d in shape[1:]]
                    if all(d is not None and d > 0 for d in feat):
                        arr = arr.reshape([len(col)] + feat)
                out[name] = arr
        return out
