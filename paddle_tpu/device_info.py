"""Device/platform introspection (platform/cpu_info.* + gpu_info.* analog).

The reference exposes core counts, flops estimates, and memory budgets per
device; here the equivalents come from the PJRT device handle plus the
chip-generation peak table (utils/flops.py)."""

import os

__all__ = [
    "cpu_count",
    "device_count",
    "device_kind",
    "peak_flops",
    "device_memory_limit",
]


def cpu_count():
    return os.cpu_count() or 1


def device_count():
    import jax

    return jax.device_count()


def device_kind(place=None):
    from .memory import _device

    d = _device(place)
    return getattr(d, "device_kind", d.platform)


def peak_flops(place=None):
    """Peak bf16 FLOPs/sec of the attached chip (None when unknown) —
    the gpu_info flops-estimate analog, used for MFU accounting."""
    from .memory import _device
    from .utils.flops import chip_peak_flops

    return chip_peak_flops(_device(place))


def device_memory_limit(place=None):
    from .memory import memory_limit

    return memory_limit(place)
