"""Program -> graphviz drawing (python/paddle/fluid/net_drawer.py parity).

Thin CLI/API over debugger.draw_block_graphviz: `draw_graph(startup, main)`
emits .dot files for both programs (the reference renders OpProto graphs
with the graphviz python package; here the .dot text is written directly —
no external dependency)."""

import argparse

from .debugger import draw_block_graphviz

__all__ = ["draw_graph"]


def draw_graph(startup_program, main_program, graph_path="./graph.dot", **kwargs):
    import os

    base, ext = os.path.splitext(graph_path)
    ext = ext or ".dot"
    paths = []
    for tag, prog in (("startup", startup_program), ("main", main_program)):
        if prog is None:
            continue
        path = "%s.%s%s" % (base, tag, ext)
        draw_block_graphviz(prog.global_block(), path=path)
        paths.append(path)
    return paths


def main():
    parser = argparse.ArgumentParser(description="draw a saved program")
    parser.add_argument("--graphviz_path", default="./graph.dot")
    args = parser.parse_args()
    import paddle_tpu as fluid

    draw_graph(
        fluid.default_startup_program(),
        fluid.default_main_program(),
        args.graphviz_path,
    )


if __name__ == "__main__":
    main()
