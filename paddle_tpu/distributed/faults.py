"""Deterministic fault injection for the RPC control plane.

The chaos harness behind tests/test_fault_tolerance.py: a TCP proxy that
sits between an RPCClient and a VarServer speaking the framework's
length-prefixed frame protocol (rpc.py) and injects faults FRAME-wise —
whole requests / replies are dropped, delayed, duplicated, or truncated,
which is how real networks and dying peers actually misbehave at this
layer.  Faults follow either an explicit per-frame schedule or a seeded
random schedule, so every chaos test is reproducible bit-for-bit.

This is the measured-evidence half of the fault-tolerance story (the TVM
lesson, PAPERS.md): liveness/eviction, retry/dedup, and checkpoint-resume
are only *claimed* capabilities until a deterministic fault schedule
exercises them.

    chan = FaultyChannel(server.endpoint,
                         schedule={"s2c": {1: "drop"}}).start()
    cli = RPCClient(chan.endpoint, timeout=1, retries=3)
    ... client transparently retries; server dedup keeps at-most-once ...
    chan.stop()

Actions (per frame index, counted per direction across the proxy's whole
lifetime so reconnects keep the schedule deterministic):

* ``pass``      — forward unchanged (the default)
* ``drop``      — swallow the frame; the peer sees silence (client
                  retries on timeout; at-most-once dedup is exercised)
* ``delay``     — forward after a SEEDED, BOUNDED per-frame latency in
                  (0, ``delay_s``]: a deterministic hash of (seed, frame
                  index) picks each delayed frame's latency, so a slow
                  network is reproducible frame-for-frame (reordering
                  pressure / deadline pressure / slow-handoff chaos legs)
* ``dup``       — forward the frame twice (duplicate req_id at the
                  server: dedup must execute once and replay the reply)
* ``truncate``  — forward roughly half the frame, then kill the
                  connection (both directions): a peer dying mid-write
* ``corrupt``   — forward the frame with one payload byte flipped: the
                  receiver's closed-type decode (or HMAC) must reject it
                  as a protocol violation and drop the connection, and
                  the sender's retry must keep the stream exactly-once —
                  bit-rot on the wire, the transport sibling of the
                  journal's crc-framed tail-skip discipline

Process-level chaos (SIGKILL of cluster children) lives in launch.py's
kill helpers.  One NON-wire action rides the same schedule machinery:
``pool_kill`` on the ``fabric`` direction (serving/router.py consumes
one fabric slot per router step) kills a serving pool's step loop —
SIGKILL-equivalent death inside the serving fabric — so fabric chaos
legs pin to the same ``PADDLE_TPU_FAULT_SEED`` as the pserver suite.
``pool_kill:<pid>`` pins the victim; bare ``pool_kill`` lets the router
pick one deterministically from ``delay_fraction(idx)``.
``pool_proc_kill`` (same ``:<pid>`` form) is the process-mode twin: a
REAL SIGKILL on the pool's worker process (``--pool-mode process``),
detected by the router's RPC-failure path rather than missed beats.
"""

import socket
import struct
import threading

_LEN = struct.Struct(">Q")

ACTIONS = ("pass", "drop", "delay", "dup", "truncate", "corrupt",
           "pool_kill", "pool_proc_kill")

# wire faults make no sense inside the fabric scheduler and vice versa
_FABRIC_ACTIONS = ("pass", "pool_kill", "pool_proc_kill")


def _valid_action(action):
    if action in ACTIONS:
        return True
    # explicit victim form: pool_kill:<pid> / pool_proc_kill:<pid>
    base, sep, arg = str(action).partition(":")
    return base in ("pool_kill", "pool_proc_kill") and sep and arg.isdigit()


class FaultSchedule:
    """Maps (direction, frame_index) -> action.

    Two layers, explicit first: ``schedule={"c2s": {3: "drop"}, "s2c":
    {...}}`` pins exact frames; anything unpinned falls through to the
    seeded random rates (``drop=0.1, dup=0.05, ...`` with ``seed``), and
    with no rates to "pass".  Frame indices count per direction from 0
    over the channel's lifetime, across reconnects.

    ``seed=None`` resolves from the ``PADDLE_TPU_FAULT_SEED`` env var
    (falling back to 0): CI pins the whole chaos subset to one seed so a
    red run reproduces bit-for-bit (scripts/ci.sh)."""

    def __init__(self, schedule=None, seed=None, drop=0.0, delay=0.0,
                 dup=0.0, truncate=0.0, corrupt=0.0, pool_kill=0.0,
                 pool_proc_kill=0.0):
        import os
        import random

        if seed is None:
            seed = int(os.environ.get("PADDLE_TPU_FAULT_SEED", "0"))
        self._explicit = {"c2s": {}, "s2c": {}, "fabric": {}}
        for direction, frames in (schedule or {}).items():
            if direction not in self._explicit:
                raise ValueError("direction must be c2s|s2c|fabric, got %r"
                                 % direction)
            for idx, action in frames.items():
                if not _valid_action(action):
                    raise ValueError("unknown fault action %r" % action)
                is_fabric = str(action).partition(":")[0] in _FABRIC_ACTIONS
                if (direction == "fabric") != is_fabric and action != "pass":
                    raise ValueError(
                        "action %r is not valid on direction %r"
                        % (action, direction))
                self._explicit[direction][int(idx)] = action
        self._rates = (
            ("drop", float(drop)), ("delay", float(delay)),
            ("dup", float(dup)), ("truncate", float(truncate)),
            ("corrupt", float(corrupt)),
        )
        self._fabric_rates = (("pool_kill", float(pool_kill)),
                              ("pool_proc_kill", float(pool_proc_kill)))
        self._seed = int(seed)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._counters = {"c2s": 0, "s2c": 0, "fabric": 0}

    def delay_fraction(self, idx):
        """Deterministic per-frame latency fraction in (0, 1]: a
        splitmix64-style hash of (seed, frame index), so a delayed
        frame's latency is a pure function of the schedule — seeded,
        bounded, reproducible (never a shared-rng draw that would race
        the pump threads' ordering)."""
        z = ((self._seed & 0xFFFFFFFFFFFFFFFF) << 32 | (idx & 0xFFFFFFFF))
        z = (z + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        z ^= z >> 31
        return ((z & 0xFFFFFF) + 1) / float(1 << 24)

    def next_action(self, direction):
        """Consume one frame slot in `direction`, return its action."""
        with self._lock:
            idx = self._counters[direction]
            self._counters[direction] += 1
            action = self._explicit[direction].get(idx)
            if action is not None:
                return idx, action
            # seeded draws happen in a single global order (under the
            # lock), so a fixed seed + a single-connection client gives a
            # reproducible fault sequence
            roll = self._rng.random()
            acc = 0.0
            rates = (self._fabric_rates if direction == "fabric"
                     else self._rates)
            for name, rate in rates:
                acc += rate
                if roll < acc:
                    return idx, name
            return idx, "pass"


def _recv_frame(sock):
    """Read one length-prefixed frame (prefix + body) or None on EOF."""
    head = b""
    while len(head) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _LEN.unpack(head)
    if n > (1 << 33):
        # length bomb from a hostile peer: forward the prefix verbatim and
        # let the real server's MAX_FRAME guard reject it
        return head
    body = bytearray()
    while len(body) < n:
        chunk = sock.recv(min(1 << 20, n - len(body)))
        if not chunk:
            return None  # peer died mid-frame: drop the partial
        body.extend(chunk)
    return head + bytes(body)


class FaultyChannel:
    """Frame-aware TCP fault-injection proxy (client <-> server).

    start() listens on 127.0.0.1:<port or 0>; point the RPCClient at
    ``chan.endpoint`` instead of the real server.  stats[] counts applied
    actions per direction for asserting a schedule actually fired."""

    def __init__(self, target_endpoint, listen="127.0.0.1:0",
                 schedule=None, seed=None, drop=0.0, delay=0.0, dup=0.0,
                 truncate=0.0, corrupt=0.0, delay_s=0.05):
        self.target = target_endpoint
        self._listen = listen
        self.sched = FaultSchedule(schedule, seed=seed, drop=drop,
                                   delay=delay, dup=dup,
                                   truncate=truncate, corrupt=corrupt)
        self.delay_s = float(delay_s)
        self.stats = {"c2s": {a: 0 for a in ACTIONS},
                      "s2c": {a: 0 for a in ACTIONS}}
        self._stats_lock = threading.Lock()
        self._srv = None
        self._accept_thread = None
        self._closing = threading.Event()
        self._conns = []  # live (client_sock, server_sock) pairs
        self._conns_lock = threading.Lock()
        self.endpoint = None

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        host, port = self._listen.rsplit(":", 1)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host or "127.0.0.1", int(port)))
        self._srv.listen(16)
        # closing a listener does NOT wake a thread blocked in accept()
        # on Linux: poll instead, so stop() returns promptly
        self._srv.settimeout(0.1)
        self.endpoint = "%s:%d" % self._srv.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="faultychannel-%s" % self.endpoint)
        self._accept_thread.start()
        return self

    def stop(self):
        self._closing.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns[:], []
        for pair in conns:
            self._kill_pair(pair)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def _kill_pair(self, pair):
        for s in pair:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # ---- data path -----------------------------------------------------
    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                client, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            client.settimeout(None)  # listener timeout must not inherit
            try:
                host, port = self.target.rsplit(":", 1)
                server = socket.create_connection((host, int(port)),
                                                  timeout=10)
            except OSError:
                client.close()
                continue
            for s in (client, server):
                try:
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            pair = (client, server)
            with self._conns_lock:
                self._conns.append(pair)
            for direction, src, dst in (("c2s", client, server),
                                        ("s2c", server, client)):
                threading.Thread(
                    target=self._pump, args=(direction, src, dst, pair),
                    daemon=True).start()

    def _note(self, direction, action):
        with self._stats_lock:
            self.stats[direction][action] += 1

    def _pump(self, direction, src, dst, pair):
        import time

        try:
            while not self._closing.is_set():
                frame = _recv_frame(src)
                if frame is None:
                    break
                idx, action = self.sched.next_action(direction)
                self._note(direction, action)
                if action == "drop":
                    continue
                if action == "delay":
                    # seeded bounded per-frame latency: delay_s is the
                    # BOUND, the frame's own hash picks the latency —
                    # delivered late, never lost (slow network, not a
                    # dead one)
                    time.sleep(self.delay_s
                               * self.sched.delay_fraction(idx))
                    dst.sendall(frame)
                elif action == "dup":
                    dst.sendall(frame)
                    dst.sendall(frame)
                elif action == "truncate":
                    # half a frame, then a dead peer: the reader sees a
                    # mid-frame EOF (ConnectionError / dropped conn)
                    dst.sendall(frame[: max(1, len(frame) // 2)])
                    break
                elif action == "corrupt":
                    # flip one byte in the PAYLOAD (never the length
                    # prefix — the framing must survive so the receiver
                    # reads a whole frame and rejects its content):
                    # decode/HMAC fails -> protocol violation -> the
                    # receiver drops the connection
                    mangled = bytearray(frame)
                    pos = _LEN.size + max(0, (len(frame) - _LEN.size) // 2)
                    pos = min(pos, len(mangled) - 1)
                    mangled[pos] ^= 0xFF
                    dst.sendall(bytes(mangled))
                else:
                    dst.sendall(frame)
        except OSError:
            pass
        finally:
            self._kill_pair(pair)
            with self._conns_lock:
                if pair in self._conns:
                    self._conns.remove(pair)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
