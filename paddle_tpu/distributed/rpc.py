"""Variable-transport RPC for the parameter-server path.

TPU-native re-design of the reference's gRPC var transport
(paddle/fluid/operators/distributed/grpc_client.h:175, grpc_server.h:46,
send_recv.proto.in): on TPU the data plane is ICI/XLA collectives, so this
layer only carries the DCN-side control plane — param/grad blocks and sparse
embedding rows between trainer hosts and parameter servers.  It is a
length-prefixed TYPED binary protocol over TCP (no external deps and no
arbitrary deserialization — the grpc_serde.cc / send_recv.proto.in role):

    [8B big-endian length][1B protocol version][optional 32B HMAC][payload]

The payload is a closed, recursively-typed encoding (tag byte per value:
none/bool/int/float/str/bytes/ndarray/list/tuple/dict).  ndarrays ship as
dtype-string + dims + raw bytes with an allowlisted dtype kind — nothing
on the wire can name a Python object, so a hostile peer gets a parse
error, not code execution.  Unknown tags, unknown versions, oversized
frames, and (when a shared secret is configured via
``PADDLE_TPU_RPC_HMAC_KEY``) bad MACs are all rejected.

Wire compression (FLAGS_comm_wire_dtype / FLAGS_comm_grad_int8): float
arrays a caller explicitly wraps in ``Bf16Wire`` / ``Int8Wire`` ship
under two additional array tags — bf16-cast payload, and int8 payload
with a per-array dequantization scale.  Both tags carry the ORIGINAL
dtype and decode straight back to it, so services never see a wire
dtype; both keep the closed-type-system contract (a garbage header is a
parse error).  The default float32 path never emits the new tags, so
its frames stay byte-identical to the pre-compression protocol and an
old-tag peer still parses them.

Zero-copy framing: the encoder can emit a SCATTER-GATHER segment list —
large array payloads ride as raw memoryviews handed to
``socket.sendmsg`` instead of being copied into an intermediate bytes —
and the receive path fills one preallocated buffer via ``recv_into``.
The byte stream is identical to the copying encoder's.

Verbs mirror the reference's SendRecvService (send_recv.proto.in:20-30):
SendVariable / GetVariable / PrefetchVariable / Barrier / Complete.
"""

import hashlib
import hmac as hmac_mod
import os
import socket
import socketserver
import struct
import threading

import numpy as np

_LEN = struct.Struct(">Q")
_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U32 = struct.Struct(">I")

PROTO_VERSION = 1
MAX_FRAME = 1 << 33  # 8 GiB: far above any param block; rejects length bombs

_T_NONE, _T_TRUE, _T_FALSE, _T_INT, _T_FLOAT = b"N", b"T", b"F", b"I", b"D"
_T_STR, _T_BYTES, _T_ARRAY, _T_LIST, _T_TUPLE, _T_DICT = (
    b"S", b"B", b"A", b"L", b"U", b"M")
# compressed-array tags (wire compression): payload is bf16-cast /
# int8-quantized, header carries the ORIGINAL float dtype it decodes
# back to.  Never emitted unless a caller wraps the value explicitly.
_T_ARRAY_BF16, _T_ARRAY_I8 = b"h", b"q"

# dtype kinds a peer may ship: bool, (u)int, float, complex — never object
_DTYPE_KINDS = frozenset("biufc")

# payloads at least this large ride as their own sendmsg segment
# (zero-copy); smaller ones inline into the header bytearray where the
# iovec bookkeeping would cost more than the copy
_SG_MIN_BYTES = 2048
# sendmsg iovec batch (safely under every platform's IOV_MAX)
_IOV_BATCH = 64


_BF16_UNSET = object()
_BF16_CACHE = _BF16_UNSET  # resolves to np.dtype or None once


def _bf16():
    """The ml_dtypes bfloat16 dtype (ships with jax), resolved ONCE —
    this sits on the per-array encode/decode hot path.  None when absent:
    bf16 wire frames then fail loudly instead of mis-decoding."""
    global _BF16_CACHE
    if _BF16_CACHE is _BF16_UNSET:
        try:
            import ml_dtypes

            _BF16_CACHE = np.dtype(ml_dtypes.bfloat16)
        except ImportError:  # pragma: no cover - ml_dtypes rides with jax
            _BF16_CACHE = None
    return _BF16_CACHE


class Bf16Wire:
    """Explicit marker: ship this float array bf16-cast on the wire
    (decodes back to its original dtype on the other side).  Compression
    is always caller-opt-in — the encoder never downcasts silently."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        arr = np.ascontiguousarray(np.asarray(arr))
        if arr.dtype.kind != "f":
            raise TypeError(
                "Bf16Wire wraps float arrays, got %s" % arr.dtype)
        self.arr = arr


class Int8Wire:
    """Explicit marker: ship this pre-quantized int8 array with its
    dequantization scale; decodes to ``scale * q`` in ``orig_dtype``.
    Quantization (and the error-feedback residual) happens at the
    CALLER so the residual can stay trainer-side (ops/dist_ops.py)."""

    __slots__ = ("q", "scale", "orig_dtype")

    def __init__(self, q, scale, orig_dtype="<f4"):
        q = np.ascontiguousarray(np.asarray(q))
        if q.dtype != np.int8:
            raise TypeError("Int8Wire wraps int8 arrays, got %s" % q.dtype)
        od = np.dtype(orig_dtype)
        if od.kind != "f":
            raise TypeError(
                "Int8Wire original dtype must be float, got %s" % od)
        self.q = q
        self.scale = float(scale)
        self.orig_dtype = od


def _hmac_key():
    key = os.environ.get("PADDLE_TPU_RPC_HMAC_KEY", "")
    return key.encode() if key else None


# ---- client-side comm counters ------------------------------------------
# Deterministic evidence for the bucketing/pipelining work: round trips and
# bytes are a property of the op plan (not wall clock), so bench and the
# tier-1 smoke can assert on them without timing flakiness.  Counted in
# RPCClient._call_locked only — server handlers share _send_msg/_recv_msg,
# and counting both sides would double every in-process test.
# pserver_restarts_seen / recoveries / recovery_ms are the recovery
# observability counters (docs/FAULT_TOLERANCE.md): incarnation bumps
# observed, fenced round replays performed, and total time-to-recover.
_comm_lock = threading.Lock()
# async_sparse_sends / async_dedup_drops / async_resends are the async
# fenced-delivery evidence (docs/FAULT_TOLERANCE.md): chunks shipped with
# seq tokens, server-side fence drops the client WITNESSED (a dup ack
# means an at-least-once re-delivery was absorbed exactly-once), and
# unacked chunks re-shipped after an observed incarnation bump.
_comm_stats = {"rpc_round_trips": 0, "comm_bytes_sent": 0,
               "comm_bytes_recv": 0, "comm_bytes_saved": 0,
               "pserver_restarts_seen": 0,
               "recoveries": 0, "recovery_ms": 0.0,
               "async_sparse_sends": 0, "async_dedup_drops": 0,
               "async_resends": 0,
               # dense buckets re-shipped after a plan flip dropped
               # them as stale (ops/dist_ops.py _async_replay_dense)
               "async_dense_resends": 0,
               # elastic autoscaling (docs/FAULT_TOLERANCE.md): plan
               # re-derivations this trainer performed after observing a
               # new pserver plan epoch, their total latency, and
               # clock-only sparse frames merged per (endpoint, step)
               "replans": 0, "replan_ms": 0.0, "async_clock_merges": 0}
# per-verb round-trip breakdown (rides get_comm_stats as "rpc_verbs"):
# the collective dense-grad backend is ACCEPTED on this evidence — a
# hybrid run must show zero send/send_bucket/recv/get_bucket trips while
# prefetch/send_sparse still flow to the pserver
_comm_verbs = {}


def _bump_comm(trips=0, sent=0, recv=0, verb=None):
    with _comm_lock:
        _comm_stats["rpc_round_trips"] += trips
        _comm_stats["comm_bytes_sent"] += sent
        _comm_stats["comm_bytes_recv"] += recv
        if verb is not None and trips:
            _comm_verbs[verb] = _comm_verbs.get(verb, 0) + trips


def note_recovery(ms):
    """One fenced round replay completed after a pserver incarnation bump
    (ops/dist_ops.py): time-to-recover accumulates so bench dist legs and
    the smoke COUNTERS line surface restart cost."""
    with _comm_lock:
        _comm_stats["recoveries"] += 1
        _comm_stats["recovery_ms"] = round(
            _comm_stats["recovery_ms"] + ms, 3)


def note_async(**deltas):
    """Bump the async fenced-delivery counters (trainer-side dist ops):
    async_sparse_sends / async_dedup_drops / async_resends.  Counted at
    the CLIENT so the COUNTERS line bench legs aggregate finally sees
    async traffic — `stats`' server-side async_sends used to be the only
    record of it."""
    with _comm_lock:
        for k, v in deltas.items():
            _comm_stats[k] += v


def note_bytes_saved(n):
    """Wire-compression evidence: bytes a compressed frame did NOT ship
    vs full precision.  Counted at the sites that CHOOSE compression
    (trainer-side dist ops), never in the shared codec — the codec runs
    on both ends and counting there would double every in-process test."""
    with _comm_lock:
        _comm_stats["comm_bytes_saved"] += int(n)


# the wire dtype this process's bucket ops actually USE — recorded by
# the dist-op lowerings from the transpile plan (which may override the
# flag via DistributeTranspilerConfig), so the COUNTERS tag describes
# the wire the byte counts were measured on, not whatever the global
# flag happens to say
_wire_dtype_used = None


def note_wire_dtype(wd):
    global _wire_dtype_used
    with _comm_lock:
        _wire_dtype_used = str(wd)


def get_comm_stats():
    """Snapshot of this process's client-side RPC counters (heartbeat
    traffic excluded — it is wall-clock-paced, and these counters exist
    to be a deterministic property of the op plan).  The snapshot also
    carries a ``wire_dtype`` TAG (a string, not a counter): the wire
    the bucket ops were PLANNED with when a dist program has run
    (note_wire_dtype), else the FLAGS_comm_wire_dtype value."""
    with _comm_lock:
        out = dict(_comm_stats)
        out["rpc_verbs"] = dict(_comm_verbs)
        wd = _wire_dtype_used
    if wd is None:
        try:
            from ..flags import get_flag

            wd = str(get_flag("comm_wire_dtype"))
        except Exception:
            wd = None
    if wd is not None:
        out["wire_dtype"] = wd
    return out


def reset_comm_stats():
    global _wire_dtype_used
    with _comm_lock:
        for k in _comm_stats:
            _comm_stats[k] = 0 if not isinstance(_comm_stats[k], float) \
                else 0.0
        _comm_verbs.clear()
        _wire_dtype_used = None


# ---- pserver incarnation registry ---------------------------------------
# Every reply envelope carries the serving process's incarnation number
# (minted per pserver start, cold or restored — ps_server.py).  The
# registry records the latest incarnation observed per endpoint across
# EVERY client in this process (serial, pipelined, heartbeat senders), so
# the trainer-side dist ops can fence a sync round: a bump between a
# round's sends and its gets means the server restarted mid-round and the
# round's buckets must be replayed from the round boundary
# (docs/FAULT_TOLERANCE.md, incarnation fencing).
_incar_lock = threading.Lock()
_incarnations = {}  # endpoint -> last incarnation observed


def _note_incarnation(endpoint, inc):
    """Record an observed incarnation; returns True when it CHANGED from
    a previously-observed value (a restart was witnessed)."""
    if inc is None:
        return False
    with _incar_lock:
        prev = _incarnations.get(endpoint)
        _incarnations[endpoint] = inc
    if prev is not None and prev != inc:
        with _comm_lock:
            _comm_stats["pserver_restarts_seen"] += 1
        return True
    return False


def incarnation_of(endpoint):
    """Latest incarnation observed from `endpoint`, or None before any
    reply has been seen."""
    with _incar_lock:
        return _incarnations.get(endpoint)


def reset_incarnations():
    with _incar_lock:
        _incarnations.clear()


# ---- pserver plan-epoch registry (elastic autoscaling) ------------------
# A pserver mints a new PLAN EPOCH at the first round boundary after its
# live trainer set changes durably (eviction, admission, departure —
# ps_server.py).  Once minted, every service-level reply carries
# "pepoch"; clients note it here so the trainer-side dist ops know —
# passively, off their normal traffic — when to re-derive the comm plan
# (transpiler.derive_plan) for the new world size.  The registry is
# process-wide like the incarnation registry: heartbeat senders keep it
# fresh even while a trainer is blocked in compute.
_plan_epochs = {}  # endpoint -> newest plan epoch observed


def note_plan_reply(endpoint, reply):
    """Record the plan epoch a service reply carried (no-op for replies
    that predate elasticity or epoch 0)."""
    if not isinstance(reply, dict):
        return
    pe = reply.get("pepoch")
    if pe is None:
        return
    with _incar_lock:
        if int(pe) > _plan_epochs.get(endpoint, 0):
            _plan_epochs[endpoint] = int(pe)


def plan_epoch_of(endpoint):
    """Newest plan epoch observed from `endpoint` (0 before any mint)."""
    with _incar_lock:
        return _plan_epochs.get(endpoint, 0)


def reset_plan_epochs():
    with _incar_lock:
        _plan_epochs.clear()


class _SegWriter:
    """Scatter-gather sink for ``_encode``: header bytes accumulate in a
    bytearray, large array payloads land as their own memoryview segment
    (no intermediate ``bytes`` copy).  ``segments()`` returns the frame
    body as an ordered buffer list for ``socket.sendmsg``; joining the
    segments reproduces the bytearray encoder's output byte for byte."""

    __slots__ = ("_segs", "_cur")

    def __init__(self):
        self._segs = []
        self._cur = bytearray()

    def __iadd__(self, b):
        self._cur += b
        return self

    def add_payload(self, arr):
        """Append a contiguous ndarray's raw bytes: zero-copy memoryview
        segment when large enough, inline copy otherwise."""
        if arr.nbytes >= _SG_MIN_BYTES:
            if len(self._cur):
                self._segs.append(self._cur)
                self._cur = bytearray()
            # custom dtypes (bf16) refuse the buffer protocol: view the
            # raw bytes through a same-width integer lane first
            if arr.dtype.kind not in _DTYPE_KINDS:
                arr = arr.view(np.uint8 if arr.dtype.itemsize == 1
                               else np.dtype("<u%d" % arr.dtype.itemsize))
            self._segs.append(memoryview(arr).cast("B"))
        else:
            self._cur += arr.tobytes()

    def segments(self):
        if len(self._cur):
            self._segs.append(self._cur)
            self._cur = bytearray()
        return self._segs


def _emit_payload(out, arr):
    """Raw array bytes into either sink (bytearray or _SegWriter)."""
    if isinstance(out, _SegWriter):
        out.add_payload(arr)
    else:
        out += arr.tobytes()


def _encode_array_header(out, tag, dtype_str, arr, nbytes):
    ds = dtype_str.encode("ascii")
    out += tag + _U32.pack(len(ds)) + ds + bytes([arr.ndim])
    for d in arr.shape:
        out += _I64.pack(d)
    out += _LEN.pack(nbytes)  # u64: param blocks can exceed 4 GiB
    return out


def _encode(obj, out):
    if obj is None:
        out += _T_NONE
    elif obj is True:
        out += _T_TRUE
    elif obj is False:
        out += _T_FALSE
    elif isinstance(obj, (int, np.integer)):
        try:
            out += _T_INT + _I64.pack(int(obj))
        except struct.error:
            raise TypeError("rpc int %r exceeds 64 bits" % (obj,))
    elif isinstance(obj, (float, np.floating)):
        out += _T_FLOAT + _F64.pack(float(obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out += _T_STR + _U32.pack(len(b)) + b
    elif isinstance(obj, bytes):
        out += _T_BYTES + _U32.pack(len(obj)) + obj
    elif isinstance(obj, (list, tuple)):
        out += (_T_LIST if isinstance(obj, list) else _T_TUPLE)
        out += _U32.pack(len(obj))
        for v in obj:
            _encode(v, out)
    elif isinstance(obj, dict):
        out += _T_DICT + _U32.pack(len(obj))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError("rpc dict keys must be str, got %r" % (k,))
            _encode(k, out)
            _encode(v, out)
    elif isinstance(obj, Bf16Wire):
        bf = _bf16()
        if bf is None:
            raise TypeError("bf16 wire compression needs ml_dtypes")
        wire = np.ascontiguousarray(obj.arr.astype(bf))
        _encode_array_header(out, _T_ARRAY_BF16, obj.arr.dtype.str,
                             wire, wire.nbytes)
        _emit_payload(out, wire)
    elif isinstance(obj, Int8Wire):
        _encode_array_header(out, _T_ARRAY_I8, obj.orig_dtype.str,
                             obj.q, obj.q.nbytes)
        out += _F64.pack(obj.scale)
        _emit_payload(out, obj.q)
    else:
        # arrays last: jax/np duck-typed values normalize through asarray
        arr = np.ascontiguousarray(np.asarray(obj))
        if arr.dtype.kind not in _DTYPE_KINDS:
            raise TypeError(
                "rpc cannot ship dtype %s (kind %r)" % (arr.dtype, arr.dtype.kind))
        _encode_array_header(out, _T_ARRAY, arr.dtype.str, arr, arr.nbytes)
        _emit_payload(out, arr)
    return out


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf):
        self.buf = memoryview(buf)
        self.pos = 0

    def take(self, n):
        if self.pos + n > len(self.buf):
            raise ValueError("rpc frame truncated")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def decode(self):
        tag = bytes(self.take(1))
        if tag == _T_NONE:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_INT:
            return _I64.unpack(self.take(8))[0]
        if tag == _T_FLOAT:
            return _F64.unpack(self.take(8))[0]
        if tag == _T_STR:
            (n,) = _U32.unpack(self.take(4))
            return bytes(self.take(n)).decode("utf-8")
        if tag == _T_BYTES:
            (n,) = _U32.unpack(self.take(4))
            return bytes(self.take(n))
        if tag in (_T_LIST, _T_TUPLE):
            (n,) = _U32.unpack(self.take(4))
            items = [self.decode() for _ in range(n)]
            return items if tag == _T_LIST else tuple(items)
        if tag == _T_DICT:
            (n,) = _U32.unpack(self.take(4))
            out = {}
            for _ in range(n):
                k = self.decode()
                if not isinstance(k, str):
                    raise ValueError("rpc dict key must decode to str")
                out[k] = self.decode()
            return out
        if tag == _T_ARRAY:
            dtype, shape, nbytes = self._array_header(_DTYPE_KINDS)
            expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            if nbytes != expect:
                raise ValueError("rpc array payload size mismatch")
            data = self.take(nbytes)
            return np.frombuffer(data, dtype=dtype).reshape(shape).copy()
        if tag == _T_ARRAY_BF16:
            # bf16-cast payload decoding back to the declared float dtype
            bf = _bf16()
            if bf is None:
                raise ValueError("rpc bf16 frame but ml_dtypes unavailable")
            dtype, shape, nbytes = self._array_header("f")
            expect = int(np.prod(shape, dtype=np.int64)) * 2
            if nbytes != expect:
                raise ValueError("rpc array payload size mismatch")
            data = self.take(nbytes)
            return np.frombuffer(data, dtype=bf).astype(dtype).reshape(shape)
        if tag == _T_ARRAY_I8:
            # int8 payload + per-array scale: decodes to scale * q
            dtype, shape, nbytes = self._array_header("f")
            expect = int(np.prod(shape, dtype=np.int64))
            if nbytes != expect:
                raise ValueError("rpc array payload size mismatch")
            (scale,) = _F64.unpack(self.take(8))
            data = self.take(nbytes)
            q = np.frombuffer(data, dtype=np.int8)
            return (q.astype(dtype) * dtype.type(scale)).reshape(shape)
        raise ValueError("rpc unknown type tag %r" % tag)

    def _array_header(self, kinds):
        """Shared array-tag header: dtype string (restricted to `kinds`),
        ndim, shape, payload byte count.  A garbage dtype string is a
        parse error, never an exception escape."""
        (dn,) = _U32.unpack(self.take(4))
        try:
            dtype = np.dtype(bytes(self.take(dn)).decode("ascii"))
        except TypeError:
            raise ValueError("rpc unparseable array dtype")
        if dtype.kind not in kinds:
            raise ValueError("rpc refuses dtype %s" % dtype)
        ndim = bytes(self.take(1))[0]
        shape = tuple(_I64.unpack(self.take(8))[0] for _ in range(ndim))
        (nbytes,) = _LEN.unpack(self.take(8))
        return dtype, shape, nbytes


_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


def _sendall_segments(sock, segments):
    """sendall for a scatter-gather buffer list: hands iovec batches to
    ``socket.sendmsg`` (no joining copy), resuming mid-segment on short
    writes; platforms without sendmsg fall back to per-segment sendall."""
    views = []
    for s in segments:
        mv = s if isinstance(s, memoryview) else memoryview(s)
        if mv.nbytes:
            views.append(mv)
    if not _HAS_SENDMSG:  # pragma: no cover - POSIX always has sendmsg
        for v in views:
            sock.sendall(v)
        return
    i = 0
    while i < len(views):
        sent = sock.sendmsg(views[i:i + _IOV_BATCH])
        while i < len(views) and sent >= views[i].nbytes:
            sent -= views[i].nbytes
            i += 1
        if i < len(views) and sent:
            views[i] = views[i][sent:]


def _send_msg(sock, obj):
    from .. import profiler as _prof

    if _prof._enabled:
        with _prof.RecordEvent("rpc_serialize", cat="serialize"):
            segs = _encode(obj, _SegWriter()).segments()
    else:
        segs = _encode(obj, _SegWriter()).segments()
    total = sum(len(s) for s in segs)
    key = _hmac_key()
    if key:
        h = hmac_mod.new(key, digestmod=hashlib.sha256)
        for s in segs:
            h.update(s)
        mac = h.digest()
    else:
        mac = b""
    head = _LEN.pack(1 + len(mac) + total) + bytes([PROTO_VERSION]) + mac
    _sendall_segments(sock, [head] + segs)
    return len(head) + total


# upfront recv buffer cap: the frame length is PEER-CONTROLLED, and
# zero-filling an 8 GiB claim (MAX_FRAME) before a single payload byte
# arrives would be a memory bomb — beyond this, the buffer doubles only
# as data actually lands, so memory stays proportional to received bytes
_RECV_PREALLOC = 16 << 20


def _recv_exact(sock, n):
    """Read exactly n bytes via recv_into on ONE preallocated buffer (no
    chunk-list join).  The preallocation is capped: a length header
    claiming gigabytes commits nothing until the peer actually delivers
    (the buffer grows by doubling, bounded by bytes received)."""
    buf = bytearray(min(n, _RECV_PREALLOC))
    view = memoryview(buf)
    got = 0
    while got < n:
        if got == len(buf):
            view.release()  # a bytearray with an exported view can't grow
            new = bytearray(min(n, len(buf) * 2))
            new[:got] = buf
            buf = new
            view = memoryview(buf)
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed connection")
        got += r
    return buf


def _recv_msg(sock):
    return _recv_msg_sized(sock)[0]


def _recv_msg_sized(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n < 1 or n > MAX_FRAME:
        raise ValueError("rpc frame length %d out of bounds" % n)
    frame = memoryview(_recv_exact(sock, n))
    version = frame[0]
    if version != PROTO_VERSION:
        raise ValueError(
            "rpc protocol version %d unsupported (want %d)"
            % (version, PROTO_VERSION))
    body = frame[1:]
    key = _hmac_key()
    if key:
        if len(body) < 32:
            raise ValueError("rpc frame missing MAC")
        mac, body = body[:32], body[32:]
        want = hmac_mod.new(key, body, hashlib.sha256).digest()
        if not hmac_mod.compare_digest(mac, want):
            raise ValueError("rpc MAC verification failed")
    r = _Reader(body)
    obj = r.decode()
    if r.pos != len(r.buf):
        raise ValueError("rpc frame has %d trailing bytes"
                         % (len(r.buf) - r.pos))
    return obj, _LEN.size + n


class _InFlight:
    """Dedup-table entry: created before dispatch so a timed-out client's
    retry waits on the original execution instead of re-executing a
    non-idempotent verb concurrently (e.g. double-registering a trainer
    into the next barrier round)."""

    __slots__ = ("done", "result")

    def __init__(self):
        self.done = threading.Event()
        self.result = None


def _execute_once(dedup, dedup_lock, service, verb, kwargs, req_id):
    """At-most-once dispatch shared by both transports: a client retry
    after a dropped reply must not re-apply non-idempotent verbs (grad
    sends, barriers) — the in-flight marker is recorded BEFORE dispatch,
    so a retry always finds it and waits for the original result instead
    of re-executing.  Completed entries trim oldest-first past 4096."""
    with dedup_lock:
        entry = dedup.get(req_id)
        owner = entry is None
        if owner:
            entry = dedup[req_id] = _InFlight()
    if owner:
        try:
            entry.result = service.handle(verb, **kwargs)
        finally:
            entry.done.set()
        with dedup_lock:
            if len(dedup) > 4096:
                for rid in list(dedup):
                    if len(dedup) <= 4096:
                        break
                    if dedup[rid].done.is_set():
                        del dedup[rid]
    else:
        entry.done.wait()
    return entry.result


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server = self.server
        service = server.service
        try:
            while True:
                verb, kwargs, req_id = _recv_msg(self.request)
                if verb == "__close__":
                    return
                result = _execute_once(server.dedup, server.dedup_lock,
                                       service, verb, kwargs, req_id)
                # replies carry the req_id: a duplicated request frame (a
                # retransmitting network / fault injection) produces an
                # EXTRA reply, and without the id the client would pair it
                # with its next request and read results off-by-one.
                # They also carry the service's incarnation (0 for
                # services without one) so clients can fence restarts.
                _send_msg(self.request,
                          ("__reply__", req_id, result,
                           getattr(service, "incarnation", 0)))
        except (ConnectionError, EOFError, ValueError):
            # ValueError = malformed/hostile frame (bad tag, bad version,
            # bad MAC, length bomb): the framing can no longer be trusted,
            # so drop this connection; the server keeps serving others
            return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        import collections

        self.dedup = collections.OrderedDict()  # req_id -> _InFlight
        self.dedup_lock = threading.Lock()


class VarServer:
    """Threaded TCP server dispatching verbs to a service object
    (AsyncGRPCServer + RequestHandler analog, request_handler.h:131)."""

    def __init__(self, endpoint, service):
        host, port = endpoint.rsplit(":", 1)
        self._server = _Server((host or "127.0.0.1", int(port)), _Handler)
        self._server.service = service
        self._thread = None
        self.endpoint = "%s:%d" % self._server.server_address

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def wait(self):
        if self._thread is not None:
            self._thread.join()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class NativeVarServer:
    """C++-transport variant of VarServer (native/frame_server.cc): socket
    accept, frame validation, HMAC checking and reply writes run on C++
    threads with no GIL; Python worker threads only decode validated
    payloads and run the service verbs (the reference's split between the
    C++ AsyncGRPCServer and its RequestHandlers).  Same wire protocol,
    same dedup/at-most-once semantics, drop-in for VarServer."""

    def __init__(self, endpoint, service):
        from ..native import get_lib as _load_native

        lib = _load_native()
        if lib is None:
            raise RuntimeError(
                "native frame server unavailable (libpaddle_tpu_native.so "
                "failed to build) — use VarServer")
        self._lib = lib
        host, port = endpoint.rsplit(":", 1)
        key = _hmac_key() or b""
        self._h = lib.fs_create((host or "127.0.0.1").encode(), int(port),
                                key)
        if not self._h:
            raise OSError("fs_create failed for %s" % endpoint)
        self.endpoint = "%s:%d" % (host or "127.0.0.1", lib.fs_port(self._h))
        self.service = service
        self._threads = []
        self._closing = threading.Event()
        import collections

        self.dedup = collections.OrderedDict()
        self.dedup_lock = threading.Lock()
        self._h_lock = threading.Lock()
        self._h_cv = threading.Condition(self._h_lock)
        self._inflight_sends = 0

    def _pop_loop(self):
        """Single popper: drains validated requests from C++ and hands each
        to its own handler thread — blocking verbs (sync barriers waiting
        on all trainers) must never starve the pop loop, mirroring the
        Python transport's thread-per-connection behavior."""
        import ctypes

        lib = self._lib
        while not self._closing.is_set():
            req = lib.fs_next(self._h, 200)
            if not req:
                continue
            try:
                n = ctypes.c_uint64()
                ptr = lib.fs_req_data(req, ctypes.byref(n))
                body = ctypes.string_at(ptr, n.value)
                conn = lib.fs_req_conn(req)
            finally:
                lib.fs_req_free(req)
            t = threading.Thread(target=self._handle_one, args=(body, conn),
                                 daemon=True)
            t.start()

    def _handle_one(self, body, conn):
        try:
            r = _Reader(body)
            msg = r.decode()
            if r.pos != len(r.buf):  # same trailing-bytes rule as _recv_msg
                return
            verb, kwargs, req_id = msg
        except (ValueError, TypeError):
            return  # C++ validated framing; a bad payload is dropped
        if verb == "__close__":
            return
        result = _execute_once(self.dedup, self.dedup_lock, self.service,
                               verb, kwargs, req_id)
        # same reply envelope as the Python transport (see _Handler)
        payload = bytes(_encode(
            ("__reply__", req_id, result,
             getattr(self.service, "incarnation", 0)), bytearray()))
        # a handler can outlive shutdown(): take an in-flight ticket under
        # the lifecycle lock, but run the (possibly blocking) TCP write
        # OUTSIDE it — one stalled peer must not freeze other replies.
        # shutdown() waits for in-flight sends before freeing the server.
        with self._h_cv:
            h = self._h
            if not h:
                return
            self._inflight_sends += 1
        try:
            self._lib.fs_send(h, conn, payload, len(payload))
        finally:
            with self._h_cv:
                self._inflight_sends -= 1
                self._h_cv.notify_all()

    def start(self):
        t = threading.Thread(target=self._pop_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def wait(self):
        for t in self._threads:
            t.join()

    def shutdown(self):
        self._closing.set()
        for t in self._threads:  # popper exits within its 200ms poll
            t.join(timeout=5)
        with self._h_cv:
            h, self._h = self._h, None
            # wait out in-flight replies; fs_close also closes every
            # connection, which unblocks any send stalled on a dead peer
            self._h_cv.wait_for(lambda: self._inflight_sends == 0,
                                timeout=10)
        if h:
            self._lib.fs_close(h)


def make_var_server(endpoint, service):
    """Transport selector: the C++ frame server when
    PADDLE_TPU_NATIVE_RPC=1 and the native lib builds, else the Python
    socketserver transport.  Both speak the identical wire protocol."""
    if os.environ.get("PADDLE_TPU_NATIVE_RPC", "0") == "1":
        try:
            return NativeVarServer(endpoint, service)
        except (RuntimeError, OSError) as e:
            import sys

            # the operator explicitly opted in — a silent fallback would
            # fake the transport they asked for
            sys.stderr.write(
                "WARNING: PADDLE_TPU_NATIVE_RPC=1 but the native frame "
                "server is unavailable (%s); falling back to the Python "
                "transport\n" % e)
    return VarServer(endpoint, service)


def _backoff_wait(attempt, base, cap=5.0):
    """Exponential backoff with jitter (AWS half-jitter rule): sleep in
    [span/2, span] where span doubles per attempt up to `cap`.  Fixed
    waits synchronize retry storms — every trainer hammering a restarting
    pserver at the same instant; the jitter decorrelates them."""
    import random

    span = min(cap, base * (2.0 ** attempt))
    return span * (0.5 + 0.5 * random.random())


class CallPolicy:
    """ONE retry/deadline policy for control-plane RPCs, shared by the
    fabric's ProcessPool backend (serving/router.py) and launch.py's
    supervisor loops — previously each caller hardcoded its own
    `deadline_s` (launch.py's scale loop pinned 5.0s with no retry, so
    a worker slow under load errored the whole supervisor tick).

    Semantics: each FULL client.call is one attempt (the call already
    replays its round-trips internally under ONE req_id, so the server's
    at-most-once dedup makes a retried non-idempotent verb — a pool
    `step`, a grad fold — execute at most once); between attempts the
    policy sleeps the half-jitter exponential backoff, and the PER-VERB
    deadline bounds the total including every backoff.  Transport
    failures retry; remote application errors ({"__error__": ...} ->
    RuntimeError) propagate immediately — retrying "unknown verb" only
    hides the bug.
    """

    def __init__(self, timeout_s=5.0, deadline_s=15.0, attempts=3,
                 backoff_base=0.05, backoff_cap=1.0,
                 verb_deadlines=None):
        self.timeout_s = float(timeout_s)
        self.deadline_s = float(deadline_s)
        self.attempts = max(1, int(attempts))
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        # per-verb overrides, e.g. {"step": 10.0, "submit": 5.0}
        self.verb_deadlines = dict(verb_deadlines or {})

    def deadline_for(self, verb):
        return float(self.verb_deadlines.get(verb, self.deadline_s))

    def call(self, client, verb, **kwargs):
        import time

        total = self.deadline_for(verb)
        deadline = time.monotonic() + total
        last = None
        for attempt in range(self.attempts):
            remaining = deadline - time.monotonic()
            if attempt and remaining <= 0:
                break
            try:
                return client.call(
                    verb,
                    timeout_s=min(self.timeout_s, max(0.05, remaining)),
                    deadline_s=max(0.05, remaining),
                    **kwargs)
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                if attempt + 1 < self.attempts:
                    wait = _backoff_wait(attempt, self.backoff_base,
                                         self.backoff_cap)
                    wait = min(wait, max(0.0,
                                         deadline - time.monotonic()))
                    time.sleep(wait)
        raise ConnectionError(
            "rpc %s to %s failed within its %.1fs policy deadline "
            "(%d attempts): %s"
            % (verb, client.endpoint, total, self.attempts, last))


class RPCClient:
    """Blocking client with one cached connection per endpoint
    (GRPCClient analog; retries replace FLAGS_max_retry)."""

    _lock = threading.Lock()
    _instances = {}

    def __init__(self, endpoint, timeout=None, retries=None, retry_wait=0.1):
        import uuid

        from ..flags import get_flag

        self.endpoint = endpoint
        # FLAGS_rpc_deadline (ms) / FLAGS_max_retry defaults
        self.timeout = timeout if timeout is not None else get_flag("rpc_deadline") / 1000.0
        # blocking verbs (barrier / sync get) wait on cluster progress
        self.barrier_timeout = max(self.timeout, 1200.0)
        self.retries = retries if retries is not None else get_flag("max_retry")
        self.retry_wait = retry_wait  # backoff BASE (grows exponentially)
        self._sock = None
        self._io_lock = threading.Lock()
        self._token = uuid.uuid4().hex
        self._req_counter = 0

    @classmethod
    def get(cls, endpoint):
        with cls._lock:
            cli = cls._instances.get(endpoint)
            if cli is None:
                cli = cls(endpoint)
                cls._instances[endpoint] = cli
            return cli

    @classmethod
    def reset_all(cls):
        stop_heartbeats()
        PipelinedClient.reset_all()
        reset_incarnations()
        with cls._lock:
            for cli in cls._instances.values():
                cli.close()
            cls._instances.clear()

    def _connect(self, deadline=None):
        """Connect with exponential backoff + jitter; `deadline` (absolute
        time.monotonic value) bounds the WHOLE loop — a per-call deadline
        must cover connect retries too, not just round-trips."""
        import time

        host, port = self.endpoint.rsplit(":", 1)
        last = None
        for attempt in range(self.retries):
            if deadline is not None and time.monotonic() >= deadline:
                break
            try:
                timeout = self.timeout
                if deadline is not None:
                    timeout = max(0.05, min(timeout,
                                            deadline - time.monotonic()))
                sock = socket.create_connection(
                    (host, int(port)), timeout=timeout
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError as e:
                last = e
                # cap connect backoff at 1s: with the default max_retry
                # (30) a dead, non-restarting endpoint fails in ~20s —
                # persistence for real restart windows comes from raising
                # FLAGS_max_retry, not from ballooning every failure
                wait = _backoff_wait(attempt, self.retry_wait, cap=1.0)
                if deadline is not None:
                    wait = min(wait, max(0.0, deadline - time.monotonic()))
                time.sleep(wait)
        raise ConnectionError(
            "cannot reach %s after %d tries: %s"
            % (self.endpoint, self.retries, last)
        )

    def call(self, verb, timeout_s=None, deadline_s=None, **kwargs):
        """One RPC round-trip.  `timeout_s` overrides the socket timeout
        for this call — blocking verbs (sync barriers, gated gets) wait on
        cluster progress, not network latency, and must not be bounded by
        FLAGS_rpc_deadline.  `deadline_s` bounds the TOTAL call including
        every connect retry and round-trip replay (per-call deadline
        propagation: without it a call can take retries x timeout)."""
        from ..flags import get_flag

        if get_flag("enable_rpc_profiler"):
            from ..profiler import RecordEvent

            with RecordEvent("rpc_" + verb, cat="comm"):
                return self._call_locked(verb, timeout_s, kwargs, deadline_s)
        return self._call_locked(verb, timeout_s, kwargs, deadline_s)

    def _call_locked(self, verb, timeout_s, kwargs, deadline_s=None):
        import time

        with self._io_lock:
            self._req_counter += 1
            req_id = "%s:%d" % (self._token, self._req_counter)
            # reconnect + replay the round-trip a FEW times: a restarting
            # peer can accept a connection from its dying listener's
            # backlog and reset it, so one reconnect is not enough to ride
            # out a kill-and-restart window.  Connect-level persistence
            # lives in _connect() (which already loops max_retry times with
            # exponential backoff) — keeping the outer count small avoids
            # squaring the retries.  The server's dedup cache keeps replays
            # at-most-once even if an earlier copy was applied.  A genuine
            # recv timeout (peer alive but slow) is replayed at most once,
            # then surfaces.
            ROUND_TRIPS = 3
            deadline = (
                time.monotonic() + deadline_s if deadline_s is not None
                else None
            )
            last = None
            result = None

            def drop_sock():
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None

            try:
                for attempt in range(ROUND_TRIPS):
                    if (deadline is not None and attempt
                            and time.monotonic() >= deadline):
                        raise ConnectionError(
                            "rpc %s to %s deadline (%.1fs) exceeded after "
                            "%d attempts: %s"
                            % (verb, self.endpoint, deadline_s, attempt,
                               last))
                    try:
                        if self._sock is None:
                            # a fresh connection means the peer may have
                            # RESTARTED: the server re-resolves every var
                            # name against its restored scope, so a replay
                            # after reconnect picks up checkpointed state
                            self._sock = self._connect(deadline=deadline)
                        eff = timeout_s
                        if deadline is not None:
                            left = max(0.05, deadline - time.monotonic())
                            eff = min(eff, left) if eff is not None else \
                                min(self.timeout, left)
                        if eff is not None:
                            self._sock.settimeout(eff)
                        sent = _send_msg(self._sock, (verb, kwargs, req_id))
                        result, recvd = _recv_msg_sized(self._sock)
                        # unwrap the reply envelope, discarding STALE
                        # replies: a duplicated request frame yields an
                        # extra reply whose req_id pairs it with a past
                        # call, not this one.  Envelopes are
                        # (__reply__, req_id, result[, incarnation]) — the
                        # 3-tuple form is the pre-incarnation wire format.
                        while (isinstance(result, tuple)
                               and len(result) in (3, 4)
                               and result[0] == "__reply__"
                               and result[1] != req_id):
                            result, more = _recv_msg_sized(self._sock)
                            recvd += more
                        if (isinstance(result, tuple)
                                and len(result) in (3, 4)
                                and result[0] == "__reply__"):
                            if len(result) == 4:
                                _note_incarnation(self.endpoint, result[3])
                            result = result[2]
                        # heartbeats are wall-clock-paced background
                        # liveness and register is once-per-contact
                        # control traffic — neither is op-plan traffic,
                        # and counting them would make the
                        # "deterministic" counters vary with run
                        # duration / restart history
                        if verb not in ("heartbeat", "register"):
                            _bump_comm(trips=1, sent=sent, recv=recvd,
                                       verb=verb)
                        break
                    except socket.timeout:
                        drop_sock()
                        if attempt >= 1:
                            raise
                    except ValueError:
                        # protocol violation (bad version/tag/length from
                        # the peer): the stream may be mid-frame, so the
                        # cached connection is desynced — drop it and
                        # surface immediately (not transient, no retry)
                        drop_sock()
                        raise
                    except (ConnectionError, OSError) as e:
                        last = e
                        drop_sock()
                        if attempt + 1 < ROUND_TRIPS:
                            wait = _backoff_wait(attempt, self.retry_wait)
                            if deadline is not None:
                                wait = min(
                                    wait,
                                    max(0.0, deadline - time.monotonic()))
                            time.sleep(wait)
                else:
                    raise ConnectionError(
                        "rpc %s to %s failed after %d round-trip attempts: %s"
                        % (verb, self.endpoint, ROUND_TRIPS, last)
                    )
            finally:
                if (timeout_s is not None or deadline is not None) \
                        and self._sock is not None:
                    try:
                        self._sock.settimeout(self.timeout)
                    except OSError:
                        pass
        if isinstance(result, dict) and result.get("__error__"):
            raise RuntimeError(
                "remote error from %s: %s" % (self.endpoint, result["__error__"])
            )
        return result

    # ---- SendRecvService verbs ------------------------------------------
    def send_var(self, name, value, trainer_id=0):
        return self.call("send", name=name, value=value, trainer_id=trainer_id)

    def get_var(self, name, trainer_id=0):
        # sync-mode gets block until the optimize round completes
        return self.call("get", timeout_s=self.barrier_timeout,
                         name=name, trainer_id=trainer_id)

    def prefetch(self, table, ids, trainer_id=0):
        return self.call("prefetch", table=table, ids=ids, trainer_id=trainer_id)

    def send_sparse(self, table, ids, rows, trainer_id=0):
        return self.call(
            "send_sparse", table=table, ids=ids, rows=rows, trainer_id=trainer_id
        )

    def barrier(self, kind, trainer_id=0):
        # barriers wait for every live trainer: bounded by straggler time,
        # not rpc_deadline
        return self.call("barrier", timeout_s=self.barrier_timeout,
                         kind=kind, trainer_id=trainer_id)

    def checkpoint_notify(self, dir=None, trainer_id=0):
        """Ask the pserver to snapshot its shard (checkpoint_notify_op.cc)."""
        return self.call("checkpoint_notify", dir=dir, trainer_id=trainer_id)

    def heartbeat(self, trainer_id=0, deadline_s=None):
        """Liveness ping: tells the pserver this trainer is alive so it
        is not evicted from the sync round (go/master trainer-lease
        analog, inverted: the SERVER tracks trainer leases here)."""
        return self.call("heartbeat", deadline_s=deadline_s,
                         trainer_id=trainer_id)

    def register(self, trainer_id=0):
        """Handshake + elastic (re)join: declare a FRESH trainer
        incarnation to the pserver.  The server resets this trainer's
        per-step fold fences; an evicted/completed id is readmitted into
        the live set — blocking until the next round boundary so barrier
        totals never change mid-round.  The reply's envelope incarnation
        seeds the client-side fence baseline."""
        return self.call("register", timeout_s=self.barrier_timeout,
                         trainer_id=trainer_id)

    def complete(self, trainer_id=0):
        return self.call("complete", trainer_id=trainer_id)

    def close(self):
        with self._io_lock:
            if self._sock is not None:
                try:
                    _send_msg(self._sock, ("__close__", {}, ""))
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class PipelinedClient:
    """Windowed in-flight RPC to one endpoint (the async gRPC completion
    queue role, grpc_client.h AsyncSendVar/Wait): up to
    FLAGS_comm_inflight calls outstanding at once, each on its OWN
    connection+worker so bucket N+1 serializes and ships while bucket N
    is on the wire.  submit() returns a future; drain() joins every
    outstanding call and surfaces the first failure.

    Each worker is a full RPCClient, so per-call retry/backoff/deadline
    hardening and the server's req_id dedup (at-most-once) hold exactly
    as on the serial path — pipelining changes WHEN calls overlap, not
    their delivery semantics.  Call-completion ORDER across the window is
    unspecified; callers that need a happens-before edge (barriers, gets
    after sends) drain first."""

    _lock = threading.Lock()
    _instances = {}

    def __init__(self, endpoint, window=None, timeout=None, retries=None,
                 retry_wait=0.1):
        from ..flags import get_flag

        self.endpoint = endpoint
        w = window if window is not None else get_flag("comm_inflight")
        self.window = max(1, int(w))
        # worker-client knobs (tests pin small timeouts under fault
        # injection); None = the RPCClient flag defaults
        self._client_opts = (timeout, retries, retry_wait)
        self._pool = None
        self._pool_lock = threading.Lock()
        self._pending = []
        self._clients = []  # worker-thread RPCClients, for close()
        self._tls = threading.local()

    @classmethod
    def get(cls, endpoint):
        with cls._lock:
            cli = cls._instances.get(endpoint)
            if cli is None:
                cli = cls._instances[endpoint] = cls(endpoint)
            return cli

    @classmethod
    def reset_all(cls):
        with cls._lock:
            insts = list(cls._instances.values())
            cls._instances.clear()
        for inst in insts:
            inst.close()

    def _worker_client(self):
        cli = getattr(self._tls, "cli", None)
        if cli is None:
            timeout, retries, retry_wait = self._client_opts
            cli = self._tls.cli = RPCClient(
                self.endpoint, timeout=timeout, retries=retries,
                retry_wait=retry_wait)
            with self._pool_lock:
                self._clients.append(cli)
        return cli

    def _ensure_pool(self):
        with self._pool_lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=self.window,
                    thread_name_prefix="rpc-inflight-%s" % self.endpoint)
            return self._pool

    def submit(self, verb, timeout_s=None, **kwargs):
        """Queue one call into the window; returns a Future.  With the
        window full the pool queues it (still submitted, just not yet on
        the wire) — the cap bounds CONCURRENCY (connections + frames
        being serialized at once), not memory: queued tasks keep their
        payload arrays alive until a worker picks them up."""
        pool = self._ensure_pool()
        fut = pool.submit(self._run_one, verb, timeout_s, kwargs)
        with self._pool_lock:
            self._pending.append(fut)
        return fut

    def _run_one(self, verb, timeout_s, kwargs):
        return self._worker_client().call(verb, timeout_s=timeout_s,
                                          **kwargs)

    def drain(self):
        """Wait out every outstanding call; returns their results in
        submit order and raises the FIRST failure (after letting the rest
        finish, so a retrying straggler can't leak into the next round)."""
        with self._pool_lock:
            pending, self._pending = self._pending, []
        err = None
        results = []
        for fut in pending:
            try:
                results.append(fut.result())
            except BaseException as e:
                if err is None:
                    err = e
        if err is not None:
            raise err
        return results

    def close(self):
        with self._pool_lock:
            pool, self._pool = self._pool, None
            clients, self._clients = self._clients, []
            self._pending = []
        if pool is not None:
            pool.shutdown(wait=True)
        for cli in clients:
            try:
                cli.close()
            except Exception:
                pass


# ---- trainer liveness heartbeats --------------------------------------
# One background sender per (endpoint, trainer_id): beats every
# FLAGS_heartbeat_interval seconds on its OWN connection — the shared
# RPCClient serializes calls under _io_lock, so a heartbeat riding it
# would queue behind a blocking sync barrier and the pserver would see
# exactly the silence it is trying to detect.
_hb_lock = threading.Lock()
_hb_senders = {}  # (endpoint, trainer_id) -> (threading.Event, Thread)


def ensure_heartbeat(endpoint, trainer_id=0):
    """Idempotently start the liveness sender for one pserver endpoint.
    Called from the trainer-side dist ops on first contact; a no-op when
    FLAGS_heartbeat_interval is 0."""
    from ..flags import get_flag

    interval = float(get_flag("heartbeat_interval"))
    if interval <= 0:
        return None
    key = (endpoint, int(trainer_id))
    with _hb_lock:
        if key in _hb_senders:
            return _hb_senders[key][1]
        stop = threading.Event()

        def beat():
            # private client: small retry budget, short deadlines — a
            # down pserver must not back the sender up past its period
            cli = RPCClient(endpoint, timeout=max(1.0, interval),
                            retries=2, retry_wait=min(0.1, interval / 4))
            try:
                while True:
                    try:
                        r = cli.heartbeat(trainer_id=int(trainer_id),
                                          deadline_s=2 * interval)
                        # beats double as the plan-epoch news feed: a
                        # trainer blocked in compute still learns a
                        # membership change before its next send
                        note_plan_reply(endpoint, r)
                        if isinstance(r, dict) and r.get("live") is False:
                            # the pserver evicted this trainer and will
                            # never re-admit it: stop wasting beats (the
                            # next data verb raises the evicted error)
                            return
                    except Exception:
                        # unreachable / restarting peer: keep beating —
                        # the reconnect inside call() rides out restarts
                        pass
                    if stop.wait(interval):
                        return
            finally:
                try:
                    cli.close()
                except Exception:
                    pass

        t = threading.Thread(target=beat, daemon=True,
                             name="heartbeat-%s-%s" % (endpoint, trainer_id))
        _hb_senders[key] = (stop, t)
        t.start()
        return t


def stop_heartbeats():
    """Stop every liveness sender (trainer exit / Executor.close path —
    a completed trainer must fall silent so tests and restarts start
    clean; the pserver already removed it from the live set)."""
    with _hb_lock:
        senders = list(_hb_senders.values())
        _hb_senders.clear()
    for stop, t in senders:
        stop.set()
    for _, t in senders:
        t.join(timeout=5)
