"""Variable-transport RPC for the parameter-server path.

TPU-native re-design of the reference's gRPC var transport
(paddle/fluid/operators/distributed/grpc_client.h:175, grpc_server.h:46,
send_recv.proto.in): on TPU the data plane is ICI/XLA collectives, so this
layer only carries the DCN-side control plane — param/grad blocks and sparse
embedding rows between trainer hosts and parameter servers.  It is a
length-prefixed binary protocol over TCP (no external deps): each message is

    [8-byte big-endian length][pickled (verb, kwargs) payload]

with numpy arrays shipped via pickle protocol 5 (zero-copy out-of-band
buffers are unnecessary at control-plane rates).

Verbs mirror the reference's SendRecvService (send_recv.proto.in:20-30):
SendVariable / GetVariable / PrefetchVariable / Barrier / Complete.
"""

import pickle
import socket
import socketserver
import struct
import threading

_LEN = struct.Struct(">Q")


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class _InFlight:
    """Dedup-table entry: created before dispatch so a timed-out client's
    retry waits on the original execution instead of re-executing a
    non-idempotent verb concurrently (e.g. double-registering a trainer
    into the next barrier round)."""

    __slots__ = ("done", "result")

    def __init__(self):
        self.done = threading.Event()
        self.result = None


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server = self.server
        service = server.service
        try:
            while True:
                verb, kwargs, req_id = _recv_msg(self.request)
                if verb == "__close__":
                    return
                # at-most-once execution: a client retry after a dropped
                # reply must not re-apply non-idempotent verbs (grad sends,
                # barriers) — the in-flight marker is recorded BEFORE
                # dispatch, so a retry always finds it and waits for the
                # original result instead of re-executing
                with server.dedup_lock:
                    entry = server.dedup.get(req_id)
                    owner = entry is None
                    if owner:
                        entry = server.dedup[req_id] = _InFlight()
                if owner:
                    try:
                        entry.result = service.handle(verb, **kwargs)
                    finally:
                        entry.done.set()
                    with server.dedup_lock:
                        # trim oldest *completed* entries only
                        if len(server.dedup) > 4096:
                            for rid in list(server.dedup):
                                if len(server.dedup) <= 4096:
                                    break
                                if server.dedup[rid].done.is_set():
                                    del server.dedup[rid]
                else:
                    entry.done.wait()
                result = entry.result
                _send_msg(self.request, result)
        except (ConnectionError, EOFError):
            return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        import collections

        self.dedup = collections.OrderedDict()  # req_id -> _InFlight
        self.dedup_lock = threading.Lock()


class VarServer:
    """Threaded TCP server dispatching verbs to a service object
    (AsyncGRPCServer + RequestHandler analog, request_handler.h:131)."""

    def __init__(self, endpoint, service):
        host, port = endpoint.rsplit(":", 1)
        self._server = _Server((host or "127.0.0.1", int(port)), _Handler)
        self._server.service = service
        self._thread = None
        self.endpoint = "%s:%d" % self._server.server_address

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def wait(self):
        if self._thread is not None:
            self._thread.join()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class RPCClient:
    """Blocking client with one cached connection per endpoint
    (GRPCClient analog; retries replace FLAGS_max_retry)."""

    _lock = threading.Lock()
    _instances = {}

    def __init__(self, endpoint, timeout=None, retries=None, retry_wait=0.3):
        import uuid

        from ..flags import get_flag

        self.endpoint = endpoint
        # FLAGS_rpc_deadline (ms) / FLAGS_max_retry defaults
        self.timeout = timeout if timeout is not None else get_flag("rpc_deadline") / 1000.0
        # blocking verbs (barrier / sync get) wait on cluster progress
        self.barrier_timeout = max(self.timeout, 1200.0)
        self.retries = retries if retries is not None else get_flag("max_retry")
        self.retry_wait = retry_wait
        self._sock = None
        self._io_lock = threading.Lock()
        self._token = uuid.uuid4().hex
        self._req_counter = 0

    @classmethod
    def get(cls, endpoint):
        with cls._lock:
            cli = cls._instances.get(endpoint)
            if cli is None:
                cli = cls(endpoint)
                cls._instances[endpoint] = cli
            return cli

    @classmethod
    def reset_all(cls):
        with cls._lock:
            for cli in cls._instances.values():
                cli.close()
            cls._instances.clear()

    def _connect(self):
        import time

        host, port = self.endpoint.rsplit(":", 1)
        last = None
        for _ in range(self.retries):
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=self.timeout
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError as e:
                last = e
                time.sleep(self.retry_wait)
        raise ConnectionError(
            "cannot reach %s after %d tries: %s"
            % (self.endpoint, self.retries, last)
        )

    def call(self, verb, timeout_s=None, **kwargs):
        """One RPC round-trip.  `timeout_s` overrides the socket timeout
        for this call — blocking verbs (sync barriers, gated gets) wait on
        cluster progress, not network latency, and must not be bounded by
        FLAGS_rpc_deadline."""
        from ..flags import get_flag

        if get_flag("enable_rpc_profiler"):
            from ..profiler import RecordEvent

            with RecordEvent("rpc_" + verb):
                return self._call_locked(verb, timeout_s, kwargs)
        return self._call_locked(verb, timeout_s, kwargs)

    def _call_locked(self, verb, timeout_s, kwargs):
        import time

        with self._io_lock:
            self._req_counter += 1
            req_id = "%s:%d" % (self._token, self._req_counter)
            # reconnect + replay the round-trip a FEW times: a restarting
            # peer can accept a connection from its dying listener's
            # backlog and reset it, so one reconnect is not enough to ride
            # out a kill-and-restart window.  Connect-level persistence
            # lives in _connect() (which already loops max_retry times) —
            # keeping the outer count small avoids squaring the retries.
            # The server's dedup cache keeps replays at-most-once even if
            # an earlier copy was applied.  A genuine recv timeout (peer
            # alive but slow) is replayed at most once, then surfaces.
            ROUND_TRIPS = 3
            last = None
            result = None

            def drop_sock():
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None

            try:
                for attempt in range(ROUND_TRIPS):
                    try:
                        if self._sock is None:
                            self._sock = self._connect()
                        if timeout_s is not None:
                            self._sock.settimeout(timeout_s)
                        _send_msg(self._sock, (verb, kwargs, req_id))
                        result = _recv_msg(self._sock)
                        break
                    except socket.timeout:
                        drop_sock()
                        if attempt >= 1:
                            raise
                    except (ConnectionError, OSError) as e:
                        last = e
                        drop_sock()
                        if attempt + 1 < ROUND_TRIPS:
                            time.sleep(self.retry_wait)
                else:
                    raise ConnectionError(
                        "rpc %s to %s failed after %d round-trip attempts: %s"
                        % (verb, self.endpoint, ROUND_TRIPS, last)
                    )
            finally:
                if timeout_s is not None and self._sock is not None:
                    try:
                        self._sock.settimeout(self.timeout)
                    except OSError:
                        pass
        if isinstance(result, dict) and result.get("__error__"):
            raise RuntimeError(
                "remote error from %s: %s" % (self.endpoint, result["__error__"])
            )
        return result

    # ---- SendRecvService verbs ------------------------------------------
    def send_var(self, name, value, trainer_id=0):
        return self.call("send", name=name, value=value, trainer_id=trainer_id)

    def get_var(self, name, trainer_id=0):
        # sync-mode gets block until the optimize round completes
        return self.call("get", timeout_s=self.barrier_timeout,
                         name=name, trainer_id=trainer_id)

    def prefetch(self, table, ids, trainer_id=0):
        return self.call("prefetch", table=table, ids=ids, trainer_id=trainer_id)

    def send_sparse(self, table, ids, rows, trainer_id=0):
        return self.call(
            "send_sparse", table=table, ids=ids, rows=rows, trainer_id=trainer_id
        )

    def barrier(self, kind, trainer_id=0):
        # barriers wait for every live trainer: bounded by straggler time,
        # not rpc_deadline
        return self.call("barrier", timeout_s=self.barrier_timeout,
                         kind=kind, trainer_id=trainer_id)

    def checkpoint_notify(self, dir=None, trainer_id=0):
        """Ask the pserver to snapshot its shard (checkpoint_notify_op.cc)."""
        return self.call("checkpoint_notify", dir=dir, trainer_id=trainer_id)

    def complete(self, trainer_id=0):
        return self.call("complete", trainer_id=trainer_id)

    def close(self):
        with self._io_lock:
            if self._sock is not None:
                try:
                    _send_msg(self._sock, ("__close__", {}, ""))
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
