"""Jitted fused pserver optimize path (the TVM operator-fusion argument
applied to the sync round's host dispatch).

``ParameterServer._run_round`` used to run one op-by-op executor program
per param BLOCK while holding the round lock: for an N-block shard that
is N executor dispatches, N feed-signature checks and N tiny XLA calls
per round — pure host overhead for what is an elementwise update rule.

This module replaces that loop, when it can prove equivalence, with ONE
compiled call per (optimizer rule, hyperparams, dtype) GROUP of blocks:
every block in a group is padded to the group's longest block and
stacked into a ``[n_blocks, max_len]`` batch, the learning rate is read
from the pserver scope ONCE per round per lr variable (per-param lr
``scale`` helpers fold into a float32 factor — the same IEEE multiply
the scale op performs), and a single jitted kernel applies the rule
across the whole stack.  The rules themselves mirror
``ops/optimizer_ops.py`` exactly — elementwise math, so padding cannot
change any real element and the default-path results stay bit-identical
to the per-block executor programs.

Shard programs the analyzer cannot prove equivalent (unknown optimizer
types, scale ops feeding anything but the lr chain, mismatched in-place
output wiring) simply stay on the per-block executor path — fusion is
an optimization, never a semantics change.  ``FLAGS_ps_fused_apply=0``
disables the whole path.
"""

import numpy as np

# optimizer op types with a fused batched kernel below; everything else
# falls back to the per-block executor program
_SUPPORTED = ("sgd", "momentum", "adagrad", "adam")

# hyperparams per rule, with the SAME defaults as ops/optimizer_ops.py —
# the kernel must compute exactly what the shard program would
_HYPER_DEFAULTS = {
    "sgd": {},
    "momentum": {"mu": 0.9, "use_nesterov": False},
    "adagrad": {"epsilon": 1e-6},
    "adam": {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
}

# per-rule slot slots: (vector slots sliced like the param, scalar slots
# — per-block [1] accumulators)
_VEC_SLOTS = {
    "sgd": (),
    "momentum": ("Velocity",),
    "adagrad": ("Moment",),
    "adam": ("Moment1", "Moment2"),
}
_SCALAR_SLOTS = {
    "sgd": (),
    "momentum": (),
    "adagrad": (),
    "adam": ("Beta1Pow", "Beta2Pow"),
}
# in-place contract the shard programs rely on: OutSlot -> InSlot
_INPLACE = {
    "ParamOut": "Param",
    "VelocityOut": "Velocity",
    "MomentOut": "Moment",
    "Moment1Out": "Moment1",
    "Moment2Out": "Moment2",
    "Beta1PowOut": "Beta1Pow",
    "Beta2PowOut": "Beta2Pow",
}


class ShardSpec:
    """One fusable shard program, reduced to its data-plane facts."""

    __slots__ = ("opt_type", "hyper", "param", "grad", "vec_slots",
                 "scalar_slots", "lr_name", "lr_factor", "numel", "dtype",
                 "key")

    def __init__(self, opt_type, hyper, param, grad, vec_slots,
                 scalar_slots, lr_name, lr_factor, numel, dtype):
        self.opt_type = opt_type
        self.hyper = hyper
        self.param = param
        self.grad = grad
        self.vec_slots = vec_slots
        self.scalar_slots = scalar_slots
        self.lr_name = lr_name
        self.lr_factor = lr_factor
        self.numel = numel
        self.dtype = dtype
        # blocks sharing (rule, hyperparams, dtype) stack into one kernel
        # call; lr differences ride in as data ([B] vector), not the key
        self.key = (opt_type, tuple(sorted(hyper.items())), dtype)


def analyze_shard(prog, grad_name):
    """Reduce one shard Program to a ShardSpec, or None when the program
    is anything but the provable pattern: optional ``scale`` ops forming
    the LearningRate chain (per-param lr), plus exactly ONE supported
    optimizer op whose outputs alias its inputs (the in-place update
    contract the executor path honors)."""
    try:
        ops = list(prog.global_block().ops)
    except Exception:
        return None
    scales = {}  # out name -> (in name, factor)
    main = None
    for op in ops:
        if op.type == "scale":
            outs = op.outputs.get("Out") or []
            ins = op.inputs.get("X") or []
            if len(outs) != 1 or len(ins) != 1:
                return None
            if float(op.attrs.get("bias", 0.0)) != 0.0:
                # scale computes scale*x + bias; the factor fold below
                # is multiply-only, so a biased scale is NOT provable
                return None
            scales[outs[0]] = (ins[0], float(op.attrs.get("scale", 1.0)))
        elif op.type in _SUPPORTED and main is None:
            main = op
        else:
            return None
    if main is None:
        return None
    # outputs must write back onto their inputs (scope in-place update)
    for oslot, islot in _INPLACE.items():
        onames = main.outputs.get(oslot)
        if not onames:
            continue
        inames = main.inputs.get(islot) or []
        if inames != onames:
            return None
    # walk the lr chain through the scale helpers; every scale op must
    # sit ON that chain (a scale mutating optimizer state is not ours)
    lr = (main.inputs.get("LearningRate") or [None])[0]
    if lr is None:
        return None
    factor = 1.0
    chain_outs = set()
    while lr in scales:
        if lr in chain_outs:  # in-place / cyclic scale: not an lr helper
            return None
        chain_outs.add(lr)
        src, f = scales[lr]
        factor *= f
        lr = src
    if len(chain_outs) > 1:
        # chained scales: folding f1*f2 host-side then ONE f32 multiply
        # is not bit-identical to the executor's sequential f32
        # multiplies — today's codegen emits at most one per param, so
        # refuse rather than weaken the bit-identity contract
        return None
    if set(scales) - chain_outs:
        return None
    param = (main.inputs.get("Param") or [None])[0]
    grad = (main.inputs.get("Grad") or [None])[0]
    if param is None or grad != grad_name:
        return None
    vec_slots, scalar_slots = [], []
    for slot in _VEC_SLOTS[main.type]:
        names = main.inputs.get(slot) or []
        if len(names) != 1:
            return None
        vec_slots.append(names[0])
    for slot in _SCALAR_SLOTS[main.type]:
        names = main.inputs.get(slot) or []
        if len(names) != 1:
            return None
        scalar_slots.append(names[0])
    pv = prog.global_block()._find_var_recursive(param)
    if pv is None:
        return None
    numel = 1
    for d in pv.shape:
        numel *= int(d)
    hyper = {k: (bool(main.attrs.get(k, d)) if isinstance(d, bool)
                 else float(main.attrs.get(k, d)))
             for k, d in _HYPER_DEFAULTS[main.type].items()}
    return ShardSpec(main.type, hyper, param, grad, tuple(vec_slots),
                     tuple(scalar_slots), lr, float(factor), numel,
                     str(pv.dtype))


# ---- batched kernels ------------------------------------------------------
# one jitted callable per (rule, hyperparams); jax re-specializes per
# stack shape automatically.  All inputs [B, L] except lr (and the adam
# pows) which are [B].  The math tracks ops/optimizer_ops.py line for
# line so fused and per-block results agree bitwise.
_kernels = {}


def _get_kernel(opt_type, hyper_items):
    key = (opt_type, hyper_items)
    fn = _kernels.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    hyper = dict(hyper_items)
    if opt_type == "sgd":
        def k(p, g, lr):
            return (p - lr[:, None] * g,)
    elif opt_type == "momentum":
        mu, nesterov = hyper["mu"], hyper["use_nesterov"]

        def k(p, g, v, lr):
            v_out = mu * v + g
            if nesterov:
                p_out = p - (g + mu * v_out) * lr[:, None]
            else:
                p_out = p - lr[:, None] * v_out
            return (p_out, v_out)
    elif opt_type == "adagrad":
        eps = hyper["epsilon"]

        def k(p, g, m, lr):
            m_out = m + jnp.square(g)
            return (p - lr[:, None] * g / (jnp.sqrt(m_out) + eps), m_out)
    elif opt_type == "adam":
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]

        def k(p, g, m1, m2, b1p, b2p, lr):
            lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
            m1_out = b1 * m1 + (1 - b1) * g
            m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
            p_out = p - lr_t[:, None] * m1_out / (jnp.sqrt(m2_out) + eps)
            return (p_out, m1_out, m2_out, b1p * b1, b2p * b2)
    else:  # pragma: no cover - guarded by _SUPPORTED
        raise ValueError(opt_type)
    fn = _kernels[key] = jax.jit(k)
    return fn


class FusedApply:
    """Per-server fused plan: built once from the shard programs, applied
    every sync round.  ``apply`` consumes the round's per-grad totals and
    returns whatever it could NOT fuse (the caller runs those through the
    per-block executor path)."""

    def __init__(self, shard_programs, grad_to_shard, scope):
        self.scope = scope
        self.specs = {}  # grad block name -> ShardSpec
        self.n_fallback = 0
        for gname, idx in grad_to_shard.items():
            spec = None
            if 0 <= idx < len(shard_programs):
                prog = shard_programs[idx]
                if prog is not None:
                    spec = analyze_shard(prog, gname)
            if spec is not None:
                self.specs[gname] = spec
            else:
                self.n_fallback += 1

    def _lr_value(self, spec, lr_cache):
        """Scheduled/constant lr read ONCE per round per lr var; the
        per-param factor multiplies in the param dtype (the exact IEEE
        multiply the dropped ``scale`` op performed)."""
        val = lr_cache.get(spec.lr_name)
        if val is None:
            var = self.scope.find_var(spec.lr_name)
            if var is None:
                raise KeyError(
                    "pserver scope has no lr var %s" % spec.lr_name)
            val = lr_cache[spec.lr_name] = np.asarray(var).reshape(-1)[0]
        dt = np.dtype(spec.dtype)
        lr = dt.type(val)
        if spec.lr_factor != 1.0:
            lr = lr * dt.type(spec.lr_factor)
        return lr

    def apply(self, totals):
        """Run the fused update for every fusable grad in `totals`
        (dict grad block name -> summed grad); returns the unfusable
        remainder.  Must be called with the server lock held (it mutates
        the scope), exactly like the per-block path it replaces."""
        rest = {}
        groups = {}
        for gname in sorted(totals):
            spec = self.specs.get(gname)
            if spec is None:
                rest[gname] = totals[gname]
            else:
                groups.setdefault(spec.key, []).append(
                    (spec, totals[gname]))
        lr_cache = {}
        for key in sorted(groups, key=repr):
            self._apply_group(key, groups[key], lr_cache)
        return rest

    def _apply_group(self, key, items, lr_cache):
        opt_type, hyper_items, dtype = key
        dt = np.dtype(dtype)
        n_vec = len(items[0][0].vec_slots)
        n_scalar = len(items[0][0].scalar_slots)
        B = len(items)
        L = max(spec.numel for spec, _ in items)
        stacks = [np.zeros((B, L), dt) for _ in range(2 + n_vec)]
        scalars = [np.zeros((B,), dt) for _ in range(n_scalar)]
        lr = np.zeros((B,), dt)
        for i, (spec, g) in enumerate(items):
            n = spec.numel
            stacks[0][i, :n] = np.asarray(
                self.scope.get(spec.param), dtype=dt).reshape(-1)
            stacks[1][i, :n] = np.asarray(g, dtype=dt).reshape(-1)
            for j, slot in enumerate(spec.vec_slots):
                stacks[2 + j][i, :n] = np.asarray(
                    self.scope.get(slot), dtype=dt).reshape(-1)
            for j, slot in enumerate(spec.scalar_slots):
                scalars[j][i] = np.asarray(
                    self.scope.get(slot)).reshape(-1)[0]
            lr[i] = self._lr_value(spec, lr_cache)
        kernel = _get_kernel(opt_type, hyper_items)
        outs = [np.asarray(o) for o in kernel(*stacks, *scalars, lr)]
        for i, (spec, _g) in enumerate(items):
            n = spec.numel
            self.scope.set(spec.param, outs[0][i, :n].copy())
            for j, slot in enumerate(spec.vec_slots):
                self.scope.set(slot, outs[1 + j][i, :n].copy())
            for j, slot in enumerate(spec.scalar_slots):
                self.scope.set(
                    slot, outs[1 + n_vec + j][i:i + 1].copy())
