"""Distributed runtime: DCN control plane for the TPU framework.

Two capability families from the reference (SURVEY §2.9):
* parameter-server mode — rpc.py (var transport), ps_server.py
  (listen_and_serv service); programs rewritten by
  transpiler.DistributeTranspiler.
* collective mode ("nccl2") — init_collective() wraps
  jax.distributed.initialize: the NCCL-unique-id handshake
  (gen_nccl_id_op.cc:31) is replaced by the JAX coordination service over
  DCN, after which pjit/shard_map programs use ICI/DCN XLA collectives.
"""

import os

from .rpc import RPCClient, VarServer
from .master import Master, MasterClient, MasterService
from .ps_server import ParameterServer, run_pserver

# (endpoint, trainer_id) pairs this process has sent grads to — used by
# Executor.close() to emit SendComplete like the reference
# (executor.h:91 Close -> SendComplete).
_active_endpoints = set()


def _note_endpoint(ep, trainer_id):
    key = (ep, int(trainer_id))
    first_contact = key not in _active_endpoints
    _active_endpoints.add(key)
    if first_contact:
        # register handshake: declares this FRESH trainer incarnation to
        # the pserver (resetting its per-step fold fences), seeds the
        # client-side incarnation-fence baseline from the reply envelope,
        # and — if this id was previously evicted — blocks until the
        # pserver readmits it at a round boundary (elastic rejoin,
        # docs/FAULT_TOLERANCE.md).  Best-effort against services that
        # predate the verb.
        from .rpc import RPCClient

        try:
            r = RPCClient.get(ep).register(trainer_id=int(trainer_id))
        except RuntimeError as e:
            if "_h_register" not in str(e):  # real rejection, not
                raise                        # an unknown-verb service
        else:
            # the register reply is a fresh joiner's first window on the
            # plan epoch — seed the registry so its very first step
            # re-plans for the current world instead of burning a
            # stale-plan round trip
            from .rpc import note_plan_reply

            note_plan_reply(ep, r)
            if isinstance(r, dict) and r.get("ok") is False:
                # parked for a round boundary that never came: the job
                # completed while this joiner waited.  Terminal — with
                # the live set empty its sends would each run a "round"
                # alone, silently training the final checkpointed params
                raise RuntimeError(
                    "trainer %s cannot join pserver %s: the job already "
                    "completed while the register waited for a round "
                    "boundary — nothing to rejoin" % (trainer_id, ep))
    # first pserver contact also starts this trainer's liveness sender so
    # a mid-round crash is detectable (and a live-but-slow trainer never
    # trips the pserver's eviction deadline)
    from .rpc import ensure_heartbeat

    ensure_heartbeat(ep, trainer_id)


def send_complete_all():
    from .rpc import stop_heartbeats

    stop_heartbeats()  # fall silent BEFORE complete: no post-exit beats
    for ep, tid in sorted(_active_endpoints):
        try:
            # bounded: a RETIRED pserver (live shard migration) is gone
            # for good — without a deadline the connect retries here
            # would stall every trainer's exit for the full
            # FLAGS_max_retry budget on an endpoint that owes nothing
            RPCClient.get(ep).call("complete", trainer_id=tid,
                                   deadline_s=10.0)
        except Exception:
            pass
    _active_endpoints.clear()


def init_collective(trainer_endpoints=None, current_endpoint=None, trainer_id=None):
    """Multi-host collective bootstrap (nccl2-mode analog).

    Reads the reference's cluster env contract when args are omitted:
    PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINER_ID.
    Calls jax.distributed.initialize(coordinator, num_processes, process_id)
    with the rank-0 endpoint as coordinator — the gen_nccl_id handshake
    re-expressed over the JAX coordination service.
    """
    import jax

    if trainer_endpoints is None:
        trainer_endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
    if isinstance(trainer_endpoints, str):
        trainer_endpoints = trainer_endpoints.split(",")
    trainer_endpoints = [e.strip() for e in trainer_endpoints if e.strip()]
    if current_endpoint is None:
        current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
    if trainer_id is None:
        trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if len(trainer_endpoints) <= 1:
        return  # single host: nothing to do
    from ..parallel.collective import _enable_cpu_cross_process_collectives

    _enable_cpu_cross_process_collectives()
    jax.distributed.initialize(
        coordinator_address=trainer_endpoints[0],
        num_processes=len(trainer_endpoints),
        process_id=trainer_id,
    )


class TrainingRole:
    """PADDLE_TRAINING_ROLE env contract (fluid_benchmark.py:63-100)."""

    TRAINER = "TRAINER"
    PSERVER = "PSERVER"

    @staticmethod
    def current():
        return os.environ.get("PADDLE_TRAINING_ROLE", TrainingRole.TRAINER)
