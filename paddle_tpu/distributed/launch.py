"""Multi-process training launcher — the TPU-native analog of the
reference's cluster tooling (`paddle/scripts/submit_local.sh.in` `paddle`
CLI wrapper and `paddle/scripts/cluster_train/` fabric launchers): one
command that spawns a local cluster with the PADDLE_* env contract wired.

Two modes:

- collective (default, the "nccl2"/multi-host DP path):
    python -m paddle_tpu.distributed.launch --nproc 2 train.py [args...]
  Each rank gets PADDLE_TRAINER_ID / PADDLE_TRAINERS /
  PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT; scripts call
  `paddle_tpu.distributed.init_collective()` (rank-0 endpoint is the
  jax.distributed coordinator).

- pserver (the transpiler's parameter-server path):
    python -m paddle_tpu.distributed.launch --mode pserver \
        --nproc 2 --pservers 2 train.py [args...]
  Spawns pserver roles first (PADDLE_TRAINING_ROLE=PSERVER with
  PADDLE_CURRENT_ENDPOINT), waits for their ports, then trainer roles
  (PADDLE_TRAINING_ROLE=TRAINER with PADDLE_TRAINER_ID); all share
  PADDLE_PSERVER_EPS / PADDLE_TRAINERS.

Output is streamed line-by-line with a [role.rank] prefix.  The first
non-zero child exit kills the whole cluster (exception_holder.h's
fail-fast contract, process-level); the launcher returns that code.
"""

import argparse
import os
import socket
import subprocess
import sys
import threading
import time


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(endpoint, timeout=60, cluster=None):
    """Poll until the endpoint accepts connections; abort early (False)
    if any already-spawned child has died — waiting out the full timeout
    on a crashed pserver would mask its exit code."""
    host, port = endpoint.rsplit(":", 1)
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection((host, int(port)), timeout=1).close()
            return True
        except OSError:
            # any exit — clean or not — before the port binds means this
            # cluster can never come up; abort instead of burning the
            # timeout (no pserver legitimately exits before listening)
            if cluster is not None and any(
                p.poll() is not None for _, p, _ in cluster.procs
            ):
                return False
            time.sleep(0.2)
    return False


class _Cluster:
    """Spawned children with streamed output and fail-fast teardown."""

    def __init__(self):
        self.procs = []  # (tag, Popen)
        self._lock = threading.Lock()
        self.failed_rc = None

    def spawn(self, tag, cmd, env):
        proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
        )
        t = threading.Thread(target=self._pump, args=(tag, proc), daemon=True)
        t.start()
        self.procs.append((tag, proc, t))
        return proc

    def _pump(self, tag, proc):
        for line in proc.stdout:
            sys.stdout.write("[%s] %s" % (tag, line))
            sys.stdout.flush()
        rc = proc.wait()
        if rc != 0:
            with self._lock:
                if self.failed_rc is None:
                    self.failed_rc = rc
                    sys.stderr.write(
                        "[launch] %s exited rc=%d — stopping cluster\n" % (tag, rc)
                    )

    def wait(self, poll=0.2):
        """Wait for all children; kill everything on first failure."""
        while True:
            with self._lock:
                failed = self.failed_rc
            if failed is not None:
                self.kill()
                return failed
            if all(p.poll() is not None for _, p, _ in self.procs):
                for _, _, t in self.procs:
                    t.join(timeout=5)
                # first nonzero (incl. negative signal-kill codes) wins —
                # max() would mask a SIGKILLed child behind a clean peer
                for _, p, _ in self.procs:
                    if p.returncode != 0:
                        return p.returncode
                return 0
            time.sleep(poll)

    def kill(self):
        for _, p, _ in self.procs:
            if p.poll() is None:
                p.kill()
        for _, p, t in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            t.join(timeout=5)


def launch_collective(script_argv, nproc, base_env=None):
    eps = ",".join("127.0.0.1:%d" % free_port() for _ in range(nproc))
    cluster = _Cluster()
    ep_list = eps.split(",")
    for rank in range(nproc):
        env = dict(base_env or os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS=str(nproc),
            PADDLE_TRAINER_ENDPOINTS=eps,
            PADDLE_CURRENT_ENDPOINT=ep_list[rank],
        )
        cluster.spawn(
            "trainer.%d" % rank, [sys.executable, "-u"] + script_argv, env
        )
    return cluster.wait()


def launch_pserver(script_argv, nproc, n_pservers, base_env=None, sync=True):
    ports = [free_port() for _ in range(n_pservers)]
    eps = ",".join("127.0.0.1:%d" % p for p in ports)
    common = dict(base_env or os.environ)
    common.update(
        PADDLE_PSERVER_EPS=eps,
        PADDLE_TRAINERS=str(nproc),
        DIST_SYNC_MODE="1" if sync else "0",
    )
    cluster = _Cluster()
    for i, p in enumerate(ports):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="PSERVER",
            PADDLE_CURRENT_ENDPOINT="127.0.0.1:%d" % p,
        )
        cluster.spawn("pserver.%d" % i, [sys.executable, "-u"] + script_argv, env)
    for p in ports:
        if not _wait_port("127.0.0.1:%d" % p, cluster=cluster):
            sys.stderr.write("[launch] pserver port %d never opened\n" % p)
            # snapshot BEFORE kill(): the launcher's own SIGKILL of healthy
            # peers (-9) must not mask the original crash code
            dead = [pr.poll() for _, pr, _ in cluster.procs
                    if pr.poll() is not None]
            cluster.kill()
            bad = [rc for rc in dead if rc != 0]
            return bad[0] if bad else 1
    for rank in range(nproc):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="TRAINER",
            PADDLE_TRAINER_ID=str(rank),
        )
        cluster.spawn("trainer.%d" % rank, [sys.executable, "-u"] + script_argv, env)
    return cluster.wait()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn a local training cluster with the PADDLE_* env contract",
    )
    parser.add_argument("--nproc", type=int, default=2, help="trainer count")
    parser.add_argument(
        "--mode", choices=("collective", "pserver"), default="collective"
    )
    parser.add_argument(
        "--pservers", type=int, default=2, help="pserver count (pserver mode)"
    )
    parser.add_argument(
        "--async-mode", action="store_true",
        help="pserver mode: async updates (no barriers)",
    )
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    script_argv = [args.script] + args.script_args
    if args.mode == "collective":
        rc = launch_collective(script_argv, args.nproc)
    else:
        rc = launch_pserver(
            script_argv, args.nproc, args.pservers, sync=not args.async_mode
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
