"""Multi-process training launcher — the TPU-native analog of the
reference's cluster tooling (`paddle/scripts/submit_local.sh.in` `paddle`
CLI wrapper and `paddle/scripts/cluster_train/` fabric launchers): one
command that spawns a local cluster with the PADDLE_* env contract wired.

Two modes:

- collective (default, the "nccl2"/multi-host DP path):
    python -m paddle_tpu.distributed.launch --nproc 2 train.py [args...]
  Each rank gets PADDLE_TRAINER_ID / PADDLE_TRAINERS /
  PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT; scripts call
  `paddle_tpu.distributed.init_collective()` (rank-0 endpoint is the
  jax.distributed coordinator).

- pserver (the transpiler's parameter-server path):
    python -m paddle_tpu.distributed.launch --mode pserver \
        --nproc 2 --pservers 2 train.py [args...]
  Spawns pserver roles first (PADDLE_TRAINING_ROLE=PSERVER with
  PADDLE_CURRENT_ENDPOINT), waits for their ports, then trainer roles
  (PADDLE_TRAINING_ROLE=TRAINER with PADDLE_TRAINER_ID); all share
  PADDLE_PSERVER_EPS / PADDLE_TRAINERS.

Output is streamed line-by-line with a [role.rank] prefix.  The first
non-zero child exit kills the whole cluster (exception_holder.h's
fail-fast contract, process-level); the launcher returns that code.
"""

import argparse
import os
import socket
import subprocess
import sys
import threading
import time


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(endpoint, timeout=60, cluster=None):
    """Poll until the endpoint accepts connections; abort early (False)
    if any already-spawned child has died — waiting out the full timeout
    on a crashed pserver would mask its exit code."""
    host, port = endpoint.rsplit(":", 1)
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection((host, int(port)), timeout=1).close()
            return True
        except OSError:
            # any exit — clean or not — before the port binds means this
            # cluster can never come up; abort instead of burning the
            # timeout (no pserver legitimately exits before listening)
            if cluster is not None and any(
                p.poll() is not None for _, p, _ in cluster.procs
            ):
                return False
            time.sleep(0.2)
    return False


class _Cluster:
    """Spawned children with streamed output and fail-fast teardown.

    Chaos hooks: `kill_one(tag)` / `schedule_kill(tag, after_s)` SIGKILL a
    single child, and tags passed to `expect_failure()` don't trip the
    fail-fast teardown — the point of a chaos run is that the SURVIVORS
    finish after a deliberate kill."""

    def __init__(self):
        self.procs = []  # (tag, Popen, pump-thread)
        self._lock = threading.Lock()
        self.failed_rc = None
        self._expected_failures = set()  # tags whose death is deliberate
        # called as (tag, rc) when a child exits nonzero — pserver mode
        # uses it to report trainer deaths to the control plane, closing
        # the window where a trainer dies BEFORE its first heartbeat
        # (never tracked, so never evicted) and would hang the sync round
        self.on_child_death = None

    def spawn(self, tag, cmd, env):
        proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
        )
        t = threading.Thread(target=self._pump, args=(tag, proc), daemon=True)
        t.start()
        self.procs.append((tag, proc, t))
        return proc

    def _pump(self, tag, proc):
        for line in proc.stdout:
            sys.stdout.write("[%s] %s" % (tag, line))
            sys.stdout.flush()
        rc = proc.wait()
        if rc != 0:
            # record the failure FIRST so fail-fast teardown isn't
            # delayed behind the (best-effort, up-to-seconds) death
            # notification RPCs
            with self._lock:
                if tag in self._expected_failures:
                    sys.stderr.write(
                        "[launch] %s exited rc=%d (expected chaos kill)\n"
                        % (tag, rc)
                    )
                elif self.failed_rc is None:
                    self.failed_rc = rc
                    sys.stderr.write(
                        "[launch] %s exited rc=%d — stopping cluster\n" % (tag, rc)
                    )
            cb = self.on_child_death
            if cb is not None:
                try:
                    cb(tag, rc)
                except Exception as e:
                    sys.stderr.write(
                        "[launch] death notification for %s failed: %s\n"
                        % (tag, e))

    def wait(self, poll=0.2):
        """Wait for all children; kill everything on first (unexpected)
        failure."""
        while True:
            with self._lock:
                failed = self.failed_rc
            if failed is not None:
                self.kill()
                return failed
            if all(p.poll() is not None for _, p, _ in self.procs):
                for _, _, t in self.procs:
                    t.join(timeout=5)
                # first nonzero (incl. negative signal-kill codes) wins —
                # max() would mask a SIGKILLed child behind a clean peer —
                # but a deliberately killed child doesn't count
                for tag, p, _ in self.procs:
                    if (p.returncode != 0
                            and tag not in self._expected_failures):
                        return p.returncode
                return 0
            time.sleep(poll)

    def kill(self):
        for _, p, _ in self.procs:
            if p.poll() is None:
                p.kill()
        for _, p, t in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            t.join(timeout=5)

    # ---- chaos helpers (fault-injection harness) ----------------------
    def proc(self, tag):
        """The Popen for one child by its [role.rank] tag."""
        for t, p, _ in self.procs:
            if t == tag:
                return p
        raise KeyError("no child tagged %r (have %s)"
                       % (tag, [t for t, _, _ in self.procs]))

    def expect_failure(self, tag):
        """Mark a child's death as deliberate: its nonzero exit neither
        tears the cluster down nor fails wait()."""
        with self._lock:
            self._expected_failures.add(tag)

    def kill_one(self, tag, sig=None):
        """SIGKILL (or `sig`) one child — simulated process death.  The
        tag is auto-marked as an expected failure."""
        import signal as _signal

        self.expect_failure(tag)
        p = self.proc(tag)
        if p.poll() is None:
            if sig is None or sig == _signal.SIGKILL:
                p.kill()
            else:
                p.send_signal(sig)
        return p

    def schedule_kill(self, tag, after_s, sig=None):
        """Arm a timer that kill_one()s `tag` after `after_s` seconds —
        the deterministic "trainer dies mid-round" chaos trigger."""
        self.proc(tag)  # a typo'd tag must fail NOW, not silently never
        # fire from the timer thread (rc=0 would read as "survivors rode
        # out the kill" when no fault was injected at all)
        self.expect_failure(tag)  # arm BEFORE the timer can race _pump
        t = threading.Timer(after_s, self.kill_one, args=(tag, sig))
        t.daemon = True
        t.start()
        return t


def _arm_chaos(cluster, chaos_kills):
    """chaos_kills: [(tag, after_s), ...] — arm deliberate child kills."""
    for tag, after_s in chaos_kills or []:
        cluster.schedule_kill(tag, after_s)


def launch_collective(script_argv, nproc, base_env=None, chaos_kills=None):
    eps = ",".join("127.0.0.1:%d" % free_port() for _ in range(nproc))
    cluster = _Cluster()
    ep_list = eps.split(",")
    for rank in range(nproc):
        env = dict(base_env or os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS=str(nproc),
            PADDLE_TRAINER_ENDPOINTS=eps,
            PADDLE_CURRENT_ENDPOINT=ep_list[rank],
        )
        cluster.spawn(
            "trainer.%d" % rank, [sys.executable, "-u"] + script_argv, env
        )
    _arm_chaos(cluster, chaos_kills)
    return cluster.wait()


def launch_pserver(script_argv, nproc, n_pservers, base_env=None, sync=True,
                   chaos_kills=None):
    ports = [free_port() for _ in range(n_pservers)]
    eps = ",".join("127.0.0.1:%d" % p for p in ports)
    common = dict(base_env or os.environ)
    common.update(
        PADDLE_PSERVER_EPS=eps,
        PADDLE_TRAINERS=str(nproc),
        DIST_SYNC_MODE="1" if sync else "0",
    )
    cluster = _Cluster()

    def notify_trainer_death(tag, rc):
        """Tell every pserver a trainer child died (the `evict` verb): a
        trainer SIGKILLed before its first heartbeat was never tracked,
        so liveness eviction can't see it — but the LAUNCHER can, and
        the report unhangs any sync barrier waiting on the ghost while
        dropping its partial round contribution (unlike `complete`).
        Best-effort with short deadlines; re-evicting is a no-op."""
        if not tag.startswith("trainer."):
            return
        from .rpc import RPCClient

        tid = int(tag.split(".", 1)[1])
        for ep in eps.split(","):
            cli = RPCClient(ep, timeout=2, retries=2, retry_wait=0.1)
            try:
                cli.call("evict", trainer_id=tid, deadline_s=5.0)
            except Exception:
                pass  # pserver may be gone too; fail-fast handles that
            finally:
                cli.close()

    cluster.on_child_death = notify_trainer_death
    for i, p in enumerate(ports):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="PSERVER",
            PADDLE_CURRENT_ENDPOINT="127.0.0.1:%d" % p,
        )
        cluster.spawn("pserver.%d" % i, [sys.executable, "-u"] + script_argv, env)
    for p in ports:
        if not _wait_port("127.0.0.1:%d" % p, cluster=cluster):
            sys.stderr.write("[launch] pserver port %d never opened\n" % p)
            # snapshot BEFORE kill(): the launcher's own SIGKILL of healthy
            # peers (-9) must not mask the original crash code
            dead = [pr.poll() for _, pr, _ in cluster.procs
                    if pr.poll() is not None]
            cluster.kill()
            bad = [rc for rc in dead if rc != 0]
            return bad[0] if bad else 1
    for rank in range(nproc):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="TRAINER",
            PADDLE_TRAINER_ID=str(rank),
        )
        cluster.spawn("trainer.%d" % rank, [sys.executable, "-u"] + script_argv, env)
    _arm_chaos(cluster, chaos_kills)
    return cluster.wait()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn a local training cluster with the PADDLE_* env contract",
    )
    parser.add_argument("--nproc", type=int, default=2, help="trainer count")
    parser.add_argument(
        "--mode", choices=("collective", "pserver"), default="collective"
    )
    parser.add_argument(
        "--pservers", type=int, default=2, help="pserver count (pserver mode)"
    )
    parser.add_argument(
        "--async-mode", action="store_true",
        help="pserver mode: async updates (no barriers)",
    )
    parser.add_argument(
        "--chaos-kill", action="append", default=[], metavar="TAG:SECONDS",
        help="fault injection: SIGKILL child TAG (e.g. trainer.1) after "
        "SECONDS; the kill is an expected failure — the run succeeds if "
        "the survivors finish (repeatable)",
    )
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    chaos_kills = []
    for spec in args.chaos_kill:
        tag, _, after = spec.rpartition(":")
        try:
            after_s = float(after)
        except ValueError:
            tag = ""
        if not tag:
            parser.error("--chaos-kill wants TAG:SECONDS, got %r" % spec)
        chaos_kills.append((tag, after_s))

    script_argv = [args.script] + args.script_args
    if args.mode == "collective":
        rc = launch_collective(script_argv, args.nproc,
                               chaos_kills=chaos_kills)
    else:
        rc = launch_pserver(
            script_argv, args.nproc, args.pservers, sync=not args.async_mode,
            chaos_kills=chaos_kills,
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
